"""Multi-process cluster serving: N OS processes over the TCP transport.

The production topology the in-process LocalCluster simulates: each
worker is a SPAWNED OS process owning one node id, its own data_path, and
its own engines/device context, talking to its peers over
cluster/tcp_transport.py sockets. `kill -9` of a worker is therefore a
real failure mode — half-written frames, connection-refused dials, a
process that vanishes without unwinding a single lock — and the
promotion / zero-acked-write-loss / partition-heal guarantees are proven
against it, not against a simulated `close()`.

Topology: `ProcCluster(n_workers)` boots the workers plus (by default) a
voting-only TIEBREAKER node living in the supervisor process — the
classic two-data-nodes-plus-tiebreaker shape, so a 2-process cluster
survives kill -9 of either data process with an intact election quorum
while the tiebreaker (ClusterState.voting_only) never holds shard
copies. The tiebreaker doubles as the supervisor's coordinating node:
client writes/searches/reads enter there and route over real sockets.
With `tiebreaker=False` the supervisor instead drives a non-member
client endpoint through the `client_*` transport actions.

Supervisor API mirrors LocalCluster where it matters:

- `kill_9(node_id)` — SIGKILL the worker process (no goodbye; its
  address file stays behind, stale, exactly like a crashed host).
- `restart(node_id)` — spawn a fresh process for that node id; it boots
  from its persisted cluster state and re-acquires copies via peer
  recovery. The supervisor re-broadcasts the current interception rules
  to it.
- `partition(*groups)` / `heal_partition()` / `drop_action(...)` /
  `set_delay(...)` — broadcast over a dedicated, never-intercepted
  control endpoint; each worker applies the rules to its OWN sender-side
  TransportIntercepts, so a partition blocks at every node's real socket
  layer symmetrically.

Device ownership: workers force the JAX platform named in
`jax_platforms` (default "cpu" — the CI shape). Passing
`jax_distributed={"coordinator_address": ..., "num_processes": ...,
"process_id": ...}` per worker initializes `jax.distributed` so each
process owns a device subset on real hardware; this is plumbing only —
CI never exercises it (no multi-host TPU in the loop) and it is honest
residue until a real pod run.
"""

from __future__ import annotations

import multiprocessing
import os
import signal
import threading
import time
from typing import Any, Callable

from .gateway import _RETRYABLE_LOCAL_TYPES, _RETRYABLE_REMOTE_TYPES
from .transport import ConnectTransportError, RemoteActionError

TIEBREAKER_ID = "tiebreaker"


class ProcClusterUnavailableError(Exception):
    """Supervisor-side retries exhausted against the process cluster."""


def _force_platform(platform: str) -> None:
    """conftest.py's dance, in-worker: the axon TPU plugin registers from
    sitecustomize at interpreter startup and overrides JAX_PLATFORMS, so
    the config must be updated (and any initialized backends cleared)
    after importing jax."""
    os.environ.setdefault("JAX_PLATFORMS", platform)
    import jax

    jax.config.update("jax_platforms", platform)
    from jax._src import xla_bridge as _xb

    if _xb.backends_are_initialized():  # pragma: no cover - defensive
        from jax.extend.backend import clear_backends

        clear_backends()


def _worker_main(cfg: dict) -> None:
    """One spawned worker: TCP endpoint + ClusterNode + stepper loop.

    Runs until a `_shutdown` control frame arrives or the supervisor
    process disappears (getppid flip). Every swallowed step error counts
    into estpu_cluster_step_errors_total — visible via `client_state`."""
    platform = cfg.get("jax_platforms") or "cpu"
    _force_platform(platform)
    dist = cfg.get("jax_distributed")
    if dist:
        import jax

        jax.distributed.initialize(**dist)
    from .cluster import ClusterNode
    from .tcp_transport import (
        FileAddressBook,
        StaticAddressBook,
        TcpTransport,
    )

    seed_addrs = cfg.get("seed_addrs")
    host, port = "127.0.0.1", 0
    if seed_addrs:
        # Multi-host form: peers resolve from the pre-agreed static map
        # (no shared addr directory), and this worker must bind exactly
        # the address the map promised for it.
        book = StaticAddressBook(seed_addrs)
        own = book.lookup(cfg["node_id"])
        if own is not None:
            host, port = own
    else:
        book = FileAddressBook(cfg["addr_dir"])
    transport = TcpTransport(
        cfg["node_id"],
        book,
        cluster_name=cfg["cluster_name"],
        default_timeout_s=cfg.get("send_timeout_s"),
        host=host,
        port=port,
        auth_key=cfg.get("auth_key"),
    )
    node = ClusterNode(
        cfg["node_id"],
        transport,
        tuple(cfg["seeds"]),
        state_path=cfg["data_path"],
        voting_only=tuple(cfg.get("voting_only", ())),
    )
    stop = threading.Event()

    def handler(from_id: str, action: str, payload: dict):
        # Control plane of the control plane: supervisor-only frames the
        # ClusterNode never sees.
        if action == "_shutdown":
            stop.set()
            return {"ok": True}
        if action == "_intercepts":
            transport.intercepts.load(payload)
            return {"ok": True}
        return node._handle(from_id, action, payload)

    transport.register(cfg["node_id"], handler)

    # Graceful stop: SIGTERM means "finish what you are doing, then
    # leave" — the rolling-restart signal, distinct from kill -9's
    # no-goodbye death. The handler only flips the stop event; the
    # drain/flush/close sequence below runs on the main thread.
    signal.signal(signal.SIGTERM, lambda _s, _f: stop.set())
    parent = os.getppid()
    interval = float(cfg.get("step_interval_s", 0.05))
    while not stop.wait(interval):
        if os.getppid() != parent:
            break  # supervisor died: no one owns this process anymore
        try:
            node.try_elect()
            if node.is_master():
                node.health_round()
            node.check_recoveries()
        # staticcheck: ignore[broad-except] daemon control-plane stepper: must survive any transient step error and retry next tick — every swallowed error is COUNTED (estpu_cluster_step_errors_total), never silent
        except Exception:
            node._step_errors.inc()
    # Drain before teardown: in-flight requests (a search mid-scatter, a
    # replica op mid-apply) finish and answer instead of dying as resets,
    # then every engine flushes segments + commit point so the restarted
    # process replays only the translog tail. A failed drain/flush must
    # never block exit — shutdown terminates, honestly degraded.
    try:
        transport.drain(timeout_s=float(cfg.get("drain_timeout_s", 5.0)))
        with node.lock:
            engines = list(node.engines.values())
        for engine in engines:
            engine.flush()
    # staticcheck: ignore[broad-except] shutdown path: a wedged drain or a flush error (disk full, injected transport.drain fault) must not keep a SIGTERM'd process alive
    except Exception:
        pass
    node.close()
    transport.close()


class ProcCluster:
    """Supervisor for a multi-process TCP cluster (LocalCluster's API
    shape over real OS processes)."""

    def __init__(
        self,
        n_workers: int = 2,
        data_path: str | None = None,
        tiebreaker: bool = True,
        cluster_name: str = "estpu-procs",
        jax_platforms: str = "cpu",
        jax_distributed: dict[str, dict] | None = None,
        step_interval_s: float = 0.05,
        send_timeout_s: float | None = 5.0,
        boot_timeout_s: float = 90.0,
        seed_addrs: dict[str, str] | None = None,
        auth_key: str | None = None,
        drain_timeout_s: float = 5.0,
    ):
        import tempfile

        from .tcp_transport import (
            FileAddressBook,
            StaticAddressBook,
            TcpTransport,
        )

        self.data_path = data_path or tempfile.mkdtemp(prefix="estpu-procs-")
        self.addr_dir = os.path.join(self.data_path, "_addr")
        self.cluster_name = cluster_name
        self.jax_platforms = jax_platforms
        self.jax_distributed = jax_distributed or {}
        self.step_interval_s = step_interval_s
        self.send_timeout_s = send_timeout_s
        self.boot_timeout_s = boot_timeout_s
        self.drain_timeout_s = drain_timeout_s
        # Shared-key wire authn rides the worker cfg (NOT just the env:
        # a spawned worker must authenticate even when the supervisor got
        # the key programmatically). None falls back to ESTPU_TRANSPORT_KEY.
        self.auth_key = auth_key
        # Multi-host form: explicit node -> "host:port" seeds replace the
        # shared-filesystem address directory (discovery is configuration,
        # like the reference's discovery.seed_hosts).
        self.seed_addrs = dict(seed_addrs) if seed_addrs else None
        self.workers = tuple(f"node-{i}" for i in range(n_workers))
        self.voting_only = (TIEBREAKER_ID,) if tiebreaker else ()
        self.seeds = self.workers + self.voting_only
        self._ctx = multiprocessing.get_context("spawn")
        self._procs: dict[str, Any] = {}
        self._lock = threading.Lock()
        self._intercept_state: dict = {}
        self._metrics_cache: tuple[float, list] | None = None
        # Lazily-built health report service (obs/health.py): holds the
        # re-election/step-error history between report rounds.
        self._health = None
        # Transition hook (obs/incidents.py) handed down by the fronting
        # Node so the incident capture law holds in the proc topology
        # too — assigned onto the lazy HealthService at first report.
        self.health_transition_hook = None
        self._closed = False
        if self.seed_addrs:
            missing = [n for n in self.seeds if n not in self.seed_addrs]
            if missing:
                raise ValueError(
                    f"seed_addrs must name every cluster member; "
                    f"missing {missing}"
                )
            self._book = StaticAddressBook(self.seed_addrs)
        else:
            self._book = FileAddressBook(self.addr_dir)
        # Dedicated control endpoint: its intercepts stay EMPTY forever,
        # so partition/heal broadcasts always reach every worker even
        # when the cluster's own channels are partitioned.
        self._ctl = TcpTransport(
            "_ctl",
            self._book,
            cluster_name=cluster_name,
            default_timeout_s=send_timeout_s,
            auth_key=auth_key,
        )
        self._ctl.start()
        for node_id in self.workers:
            self._spawn(node_id)
        self._local_node = None
        self._tb_transport = None
        self._stepper: threading.Thread | None = None
        self._stop = threading.Event()
        if tiebreaker:
            from .cluster import ClusterNode

            tb_host, tb_port = "127.0.0.1", 0
            if self.seed_addrs:
                tb_addr = self._book.lookup(TIEBREAKER_ID)
                if tb_addr is not None:
                    tb_host, tb_port = tb_addr
            self._tb_transport = TcpTransport(
                TIEBREAKER_ID,
                self._book,
                cluster_name=cluster_name,
                default_timeout_s=send_timeout_s,
                host=tb_host,
                port=tb_port,
                auth_key=auth_key,
            )
            self._local_node = ClusterNode(
                TIEBREAKER_ID,
                self._tb_transport,
                self.seeds,
                state_path=os.path.join(self.data_path, TIEBREAKER_ID),
                voting_only=self.voting_only,
            )
            self._start_tiebreaker_stepper()
        self.wait_ready()

    # ------------------------------------------------------------ workers

    def _spawn(self, node_id: str) -> None:
        cfg = {
            "node_id": node_id,
            "seeds": list(self.seeds),
            "voting_only": list(self.voting_only),
            "addr_dir": self.addr_dir,
            "data_path": os.path.join(self.data_path, node_id),
            "cluster_name": self.cluster_name,
            "jax_platforms": self.jax_platforms,
            "jax_distributed": self.jax_distributed.get(node_id),
            "step_interval_s": self.step_interval_s,
            "send_timeout_s": self.send_timeout_s,
            "seed_addrs": self.seed_addrs,
            "auth_key": self.auth_key,
            "drain_timeout_s": self.drain_timeout_s,
        }
        proc = self._ctx.Process(
            target=_worker_main, args=(cfg,), name=f"estpu-{node_id}"
        )
        proc.daemon = True
        proc.start()
        with self._lock:
            self._procs[node_id] = proc

    def _start_tiebreaker_stepper(self) -> None:
        node = self._local_node

        def loop():
            while not self._stop.wait(self.step_interval_s):
                try:
                    node.try_elect()
                    if node.is_master():
                        node.health_round()
                    node.check_recoveries()
                # staticcheck: ignore[broad-except] daemon control-plane stepper: must survive any transient step error and retry next tick — every swallowed error is COUNTED (estpu_cluster_step_errors_total), never silent
                except Exception:
                    node._step_errors.inc()

        self._stepper = threading.Thread(
            target=loop, daemon=True, name="estpu-tiebreaker-stepper"
        )
        self._stepper.start()

    def pid(self, node_id: str) -> int | None:
        with self._lock:
            proc = self._procs.get(node_id)
        return None if proc is None else proc.pid

    def wait_ready(
        self,
        timeout_s: float | None = None,
        node_ids: tuple[str, ...] | None = None,
    ) -> None:
        """Block until the given workers (default: all) answer a ping
        over their sockets."""
        deadline = time.monotonic() + (timeout_s or self.boot_timeout_s)
        for node_id in node_ids if node_ids is not None else self.workers:
            while True:
                try:
                    self._ctl.send(
                        "_ctl", node_id, "ping", {}, timeout_s=2.0
                    )
                    break
                except (ConnectTransportError, RemoteActionError) as e:
                    if time.monotonic() >= deadline:
                        raise ProcClusterUnavailableError(
                            f"worker [{node_id}] never came up: {e}"
                        ) from e
                    time.sleep(0.1)

    def kill_9(self, node_id: str) -> None:
        """Real process death: SIGKILL, no goodbye, stale address file."""
        with self._lock:
            proc = self._procs.get(node_id)
        if proc is None or proc.pid is None:
            return
        os.kill(proc.pid, signal.SIGKILL)
        proc.join(timeout=10)

    def sigterm(self, node_id: str, timeout_s: float = 20.0) -> None:
        """Graceful stop (the rolling-restart signal): SIGTERM, then wait
        for the worker's drain → translog/segment flush → close sequence
        to finish. Escalates to SIGKILL past the deadline — shutdown must
        terminate even when the drain wedges."""
        with self._lock:
            proc = self._procs.get(node_id)
        if proc is None or proc.pid is None:
            return
        try:
            os.kill(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            return
        proc.join(timeout=timeout_s)
        if proc.is_alive():
            os.kill(proc.pid, signal.SIGKILL)
            proc.join(timeout=5)

    def restart(self, node_id: str) -> None:
        """Fresh process for the node id: boots from its persisted
        cluster state, rejoins, re-acquires copies via peer recovery."""
        with self._lock:
            proc = self._procs.pop(node_id, None)
        if proc is not None and proc.is_alive():
            raise ValueError(f"[{node_id}] is still running; kill it first")
        self._spawn(node_id)
        # Wait for THIS worker only: other workers may be intentionally
        # dead (multi-failure chaos) and must not block the restart.
        self.wait_ready(node_ids=(node_id,))
        if self._intercept_state:
            # A restarted worker boots with empty interception rules;
            # converge it onto the cluster's current ruleset.
            self._send_intercepts(node_id, self._intercept_state)

    # ------------------------------------------------- interception control

    def _send_intercepts(self, node_id: str, state: dict) -> None:
        try:
            self._ctl.send(
                "_ctl", node_id, "_intercepts", state, timeout_s=5.0
            )
        except (ConnectTransportError, RemoteActionError):
            pass  # dead worker: it gets the ruleset again on restart

    def _broadcast_intercepts(self, state: dict) -> None:
        self._intercept_state = state
        for node_id in self.workers:
            self._send_intercepts(node_id, state)
        if self._local_node is not None:
            self._tb_transport.intercepts.load(state)

    def partition(self, *groups) -> None:
        """Socket-layer partition: every node refuses sends that cross
        group lines, symmetrically."""
        state = dict(self._intercept_state or {"drops": [], "delay_s": 0.0})
        state["partitions"] = [sorted(g) for g in groups]
        self._broadcast_intercepts(state)

    def heal_partition(self) -> None:
        state = dict(self._intercept_state or {})
        state["partitions"] = []
        self._broadcast_intercepts(state)

    def drop_action(self, from_id: str, to_id: str, pattern: str) -> None:
        state = dict(self._intercept_state or {})
        state.setdefault("drops", []).append([from_id, to_id, pattern])
        self._broadcast_intercepts(state)

    def clear_drops(self) -> None:
        state = dict(self._intercept_state or {})
        state["drops"] = []
        self._broadcast_intercepts(state)

    def set_delay(
        self, seconds: float, from_id: str = "*", to_id: str = "*"
    ) -> None:
        """Injected latency, broadcast to every node's sender-side
        intercepts. The all-pairs default keeps the historical global
        knob; the targeted form (``set_delay(2.0, to_id="node-1")``)
        models ONE browned-out peer: every send toward it crawls while
        healthy paths stay fast. ``set_delay(0)`` clears everything."""
        state = dict(self._intercept_state or {})
        if from_id == "*" and to_id == "*":
            state["delay_s"] = float(seconds)
            if not seconds:
                state["delays"] = []
        else:
            delays = [
                d
                for d in state.get("delays", [])
                if (d[0], d[1]) != (from_id, to_id)
            ]
            if seconds:
                delays.append([from_id, to_id, float(seconds)])
            state["delays"] = delays
        self._broadcast_intercepts(state)

    # ------------------------------------------------------------- client

    def _retry(
        self,
        fn: Callable[[], Any],
        timeout_s: float = 30.0,
        backoff_s: float = 0.05,
    ):
        """Bounded supervisor-side retry over topology-shaped failures —
        the gateway's exact classification (shared sets) — while the
        workers' own steppers drive detection/promotion between attempts
        (there is no cluster.step() to call across processes)."""
        deadline = time.monotonic() + timeout_s
        last: Exception | None = None
        while True:
            try:
                return fn()
            except RemoteActionError as e:
                if e.remote_type not in _RETRYABLE_REMOTE_TYPES:
                    raise
                last = e
            except _RETRYABLE_LOCAL_TYPES as e:
                last = e
            if time.monotonic() >= deadline:
                raise ProcClusterUnavailableError(
                    f"cluster operation failed within {timeout_s}s: {last}"
                ) from last
            time.sleep(backoff_s)

    def _send_any(self, action: str, payload: dict):
        """client_* action against any answering worker."""
        last: Exception | None = None
        for node_id in self.workers:
            try:
                return self._ctl.send("_ctl", node_id, action, payload)
            except (ConnectTransportError, RemoteActionError) as e:
                if (
                    isinstance(e, RemoteActionError)
                    and e.remote_type not in _RETRYABLE_REMOTE_TYPES
                ):
                    raise
                last = e
        raise ConnectTransportError(f"no worker answered [{action}]: {last}")

    def create_index(
        self,
        name: str,
        n_shards: int = 1,
        n_replicas: int = 1,
        mappings: dict | None = None,
        timeout_s: float = 30.0,
    ) -> dict:
        payload = {
            "name": name,
            "n_shards": n_shards,
            "n_replicas": n_replicas,
            "mappings": mappings or {},
        }
        if self._local_node is not None:
            node = self._local_node

            def do():
                return node._on_client_create_index("supervisor", payload)

        else:

            def do():
                return self._send_any("client_create_index", payload)

        return self._retry(do, timeout_s=timeout_s)

    def write(
        self,
        index: str,
        doc_id: str,
        source: dict | None,
        op: str = "index",
        timeout_s: float = 30.0,
    ) -> dict:
        if self._local_node is not None:
            node = self._local_node

            def do():
                return node.execute_write(index, doc_id, source, op=op)

        else:
            payload = {"index": index, "id": doc_id, "source": source, "op": op}

            def do():
                return self._send_any("client_write", payload)

        return self._retry(do, timeout_s=timeout_s)

    def read(self, index: str, doc_id: str, timeout_s: float = 30.0):
        if self._local_node is not None:
            node = self._local_node

            def do():
                return node.read_doc(index, doc_id)

        else:
            payload = {"index": index, "id": doc_id}

            def do():
                return self._send_any("client_read", payload)

        return self._retry(do, timeout_s=timeout_s)

    def search(self, index: str, body: dict, timeout_s: float = 30.0) -> dict:
        if self._local_node is not None:
            node = self._local_node

            def do():
                return node.search(index, body)

        else:
            payload = {"index": index, "body": body}

            def do():
                return self._send_any("client_search", payload)

        return self._retry(do, timeout_s=timeout_s)

    def state_of(self, node_id: str, timeout_s: float = 5.0) -> dict:
        """client_state of one worker (routing table, master, counters)."""
        return self._ctl.send(
            "_ctl", node_id, "client_state", {}, timeout_s=timeout_s
        )

    # --------------------------------------------- gateway-facing surface
    # The LocalCluster shape a ProcGateway / front Node expects: `hub`
    # (the coordinating transport), `nodes` (member ids), `step()` (one
    # synchronous control-plane round), `step_errors()`.

    @property
    def hub(self):
        """The coordinating endpoint cluster-facing code sends through:
        the tiebreaker's transport — INTERCEPTED like any member's, so a
        front Node's serving path honestly feels partitions/brownouts —
        or the control endpoint when no tiebreaker exists."""
        return self._tb_transport if self._tb_transport is not None else self._ctl

    @property
    def nodes(self) -> tuple[str, ...]:
        """Cluster member ids (sorted/len/iteration surface; the actual
        members live in other OS processes)."""
        return self.seeds

    def step(self) -> None:
        """One synchronous control-plane round on the supervisor-resident
        tiebreaker — the gateway's between-retries nudge (election /
        health round / recovery check). Worker processes run their own
        steppers; without a tiebreaker this is a no-op and detection is
        entirely theirs."""
        node = self._local_node
        if node is None:
            return
        node.try_elect()
        if node.is_master():
            node.health_round()
        node.check_recoveries()

    def step_errors(self) -> int:
        node = self._local_node
        return 0 if node is None else int(node._step_errors.value)

    def wait_for_status(
        self, wanted: str = "green", timeout_s: float = 60.0
    ) -> None:
        """Block until the shard summary over the tiebreaker's published
        state reaches `wanted` AND every worker is back in the
        membership — the heal barrier the chaos arcs use (`GET
        /_cluster/health?wait_for_status=green` over the REST front polls
        the same summary)."""
        from ..obs.health import shard_summary, status_at_least

        node = self._local_node
        if node is None:
            raise ProcClusterUnavailableError(
                "wait_for_status needs the supervisor-resident tiebreaker"
            )

        def ok() -> bool:
            state = node.state
            if not set(self.workers) <= set(state.nodes):
                return False
            return status_at_least(shard_summary(state)["status"], wanted)

        self.wait_for(
            ok, timeout_s=timeout_s, what=f"cluster status {wanted}"
        )

    # ------------------------------------------- cluster-scope observability

    def _fan(
        self,
        action: str,
        payload: dict | None = None,
        timeout_s: float | None = None,
    ):
        """Scatter one wire action over every worker via the `_ctl`
        endpoint (never intercepted, so observability keeps working under
        armed partitions): partial-tolerant, deadline-bounded, named
        failure entries for dead/wedged processes."""
        from .transport import scatter_nodes

        if timeout_s is None:
            timeout_s = self.send_timeout_s or 5.0

        def send(node_id: str):
            return self._ctl.send(
                "_ctl", node_id, action, dict(payload or {}),
                timeout_s=timeout_s,
            )

        return scatter_nodes(
            list(self.workers), send, action, timeout_s,
            metrics=self._ctl.metrics,
        )

    def nodes_stats(self, extra: dict[str, dict] | None = None) -> dict:
        """`GET /_nodes/stats` over the process cluster: the `node_stats`
        wire action fanned to every worker plus the supervisor-resident
        tiebreaker, with a `_nodes: {total, successful, failed}` header —
        a kill -9'd worker shows up as a named failure entry within the
        per-send deadline, never a hang. `extra` grafts additional
        sections (the REST front's own node) into the payload."""
        results, failures = self._fan("node_stats")
        nodes: dict[str, dict] = {}
        if self._local_node is not None:
            nodes[TIEBREAKER_ID] = self._local_node.node_stats_local()
        for node_id in self.workers:
            if node_id in results:
                nodes[node_id] = results[node_id]
        for name, section in (extra or {}).items():
            nodes[name] = section
        local = (1 if self._local_node is not None else 0) + len(extra or {})
        header: dict[str, Any] = {
            "total": len(self.workers) + local,
            "successful": len(results) + local,
            "failed": len(failures),
        }
        if failures:
            header["failures"] = failures
        return {
            "_nodes": header,
            "cluster_name": self.cluster_name,
            "nodes": nodes,
        }

    def health_report(
        self,
        verbose: bool = True,
        indicator: str | None = None,
        extra_inputs: dict[str, dict] | None = None,
    ) -> dict:
        """`GET /_health_report` over the process cluster: the
        `health_inputs` wire action fanned to every worker over the
        never-intercepted `_ctl` socket path plus the supervisor-resident
        tiebreaker's own inputs, interpreted by the SAME obs/health.py
        indicator functions the in-process forms use. A kill -9'd worker
        becomes a named per-indicator diagnosis within the per-send
        deadline — never a hang. ``verbose=False`` skips the worker fan
        (cheap liveness probe: statuses + symptoms from the supervisor's
        view alone)."""
        from ..obs.health import HealthContext, HealthService

        if self._health is None:
            self._health = HealthService(metrics=self._ctl.metrics)
        self._health.transition_hook = self.health_transition_hook
        node_inputs: dict[str, dict] = {}
        failures: list[dict] = []
        state = None
        coordinator = "_ctl"
        if self._local_node is not None:
            coordinator = TIEBREAKER_ID
            state = self._local_node.state
            node_inputs[TIEBREAKER_ID] = (
                self._local_node.health_inputs_local()
            )
        if verbose:
            results, failures = self._fan("health_inputs")
            for node_id in self.workers:
                if node_id in results:
                    node_inputs[node_id] = results[node_id]
        for name, inputs in (extra_inputs or {}).items():
            node_inputs.setdefault(name, inputs)
        if state is None:
            # No tiebreaker: adopt an answering worker's published state
            # for the shard/master rules — in BOTH modes (a terse probe
            # with no state would report a healthy cluster red). Verbose
            # prefers the freshest fanned section's node; terse asks the
            # workers in order until one answers.
            from .state import ClusterState

            candidates = list(self.workers)
            if verbose and results:
                candidates = sorted(
                    results,
                    key=lambda n: (
                        results[n].get("cluster_state", {}).get("term", 0),
                        results[n]
                        .get("cluster_state", {})
                        .get("version", 0),
                    ),
                    reverse=True,
                ) + [n for n in candidates if n not in results]
            for node_id in candidates:
                try:
                    raw = self.state_of(node_id)
                    state = ClusterState.from_json(raw["state"])
                    break
                except (ConnectTransportError, RemoteActionError):
                    continue
        ctx = HealthContext(
            cluster_name=self.cluster_name,
            coordinator=coordinator,
            standalone=False,
            state=state,
            expected_nodes=tuple(self.workers),
            node_inputs=node_inputs,
            fan_failures=failures,
            fanned=verbose,
        )
        return self._health.report(
            ctx, verbose=verbose, indicator=indicator
        )

    def metrics_text(
        self,
        max_age_s: float | None = None,
        extra_snapshots: tuple = (),
    ) -> str:
        """Federated `GET /_metrics`: every live worker's registry ships
        over the `metrics_wire` action and re-exposes here with a
        `node=<id>` label per series; counters additionally fold into
        `node="_cluster"` totals. The worker fan caches for
        ESTPU_METRICS_FED_TTL_S (default 0.5s) so a scrape storm cannot
        multiply fan-outs; the fan itself is deadline-bounded and runs
        only at scrape time — never on the serving hot path.
        `extra_snapshots` (WireRegistrySnapshot, e.g. the REST front's
        own registry) join the exposition and the cluster fold uncached."""
        from ..analysis.analyzers import ANALYSIS_METRICS
        from ..obs.metrics import WireRegistrySnapshot, fold_cluster_counters

        if max_age_s is None:
            max_age_s = float(
                os.environ.get("ESTPU_METRICS_FED_TTL_S", "0.5") or 0.5
            )
        now = time.monotonic()
        with self._lock:
            cached = self._metrics_cache
        if cached is not None and now - cached[0] <= max_age_s:
            snapshots = cached[1]
        else:
            results, _failures = self._fan("metrics_wire")
            snapshots = [
                WireRegistrySnapshot(
                    (results[node_id] or {}).get("families"), node=node_id
                )
                for node_id in sorted(results)
            ]
            if self._local_node is not None:
                snapshots.append(
                    WireRegistrySnapshot(
                        self._local_node.metrics.to_wire(
                            self._tb_transport.metrics
                        ),
                        node=TIEBREAKER_ID,
                    )
                )
            with self._lock:
                self._metrics_cache = (time.monotonic(), snapshots)
        merged = list(snapshots) + list(extra_snapshots)
        return self._ctl.metrics.exposition(
            ANALYSIS_METRICS, *merged, fold_cluster_counters(merged)
        )

    def hot_threads(
        self,
        threads: int = 3,
        interval_s: float = 0.5,
        snapshots: int = 10,
    ) -> str:
        """`GET /_nodes/hot_threads` over the process cluster: every
        worker samples its OWN interpreter's thread stacks; the texts
        concatenate under `::: {node}` headers, with a failure line for
        any process that could not be sampled."""
        from ..obs.hot_threads import fan_text_blocks, hot_threads_text

        payload = {
            "threads": threads,
            "interval_s": interval_s,
            "snapshots": snapshots,
        }
        local_box: dict[str, str] = {}
        sampler = None
        if self._local_node is not None:
            # Supervisor sample runs CONCURRENTLY with the fan: one
            # interval of wall clock for the whole cluster.
            local_node = self._local_node

            def sample_local() -> None:
                local_box["text"] = hot_threads_text(
                    node_name=TIEBREAKER_ID,
                    threads=threads,
                    interval_s=interval_s,
                    snapshots=snapshots,
                    metrics=local_node.metrics,
                )

            sampler = threading.Thread(target=sample_local, daemon=True)
            sampler.start()
        results, failures = self._fan(
            "hot_threads",
            payload,
            timeout_s=(self.send_timeout_s or 5.0) + float(interval_s),
        )
        blocks = []
        if sampler is not None:
            sampler.join()
            blocks.append(local_box.get("text", ""))
        blocks.extend(
            fan_text_blocks(results, failures, order=list(self.workers))
        )
        return "\n".join(blocks)

    def search_traced(
        self, index: str, body: dict, timeout_s: float = 30.0
    ) -> tuple[dict, str]:
        """Search under a ROOT trace span: (response, trace_id). The
        remote shard executions' spans land in the worker processes'
        rings; `trace(trace_id)` splices them back into one tree."""
        from ..obs.tracing import TRACER

        with TRACER.start_trace("procs.search", index=index) as root:
            out = self.search(index, body, timeout_s=timeout_s)
        return out, root.trace_id

    def trace(self, trace_id: str, fmt: str | None = None):
        """Distributed trace assembly: collect this trace's fragments
        from the supervisor's own ring and every live worker, splice ONE
        tree. None when no process buffered the trace; `fmt="chrome"`
        renders Perfetto-loadable trace-event JSON covering the whole
        cluster (one track per node)."""
        from ..obs.tracing import TRACER, chrome_trace, collect_fragments

        results, failures = self._fan(
            "trace_fragment", {"trace_id": trace_id}
        )
        spans, collected = collect_fragments(TRACER.get(trace_id), results)
        if collected:
            self._ctl.metrics.counter(
                "estpu_trace_fragments_collected_total",
                "Trace-fragment spans collected from cluster nodes",
            ).inc(collected)
        if not spans:
            return None
        if fmt == "chrome":
            return chrome_trace(spans)
        tb = 1 if self._local_node is not None else 0
        header: dict[str, Any] = {
            "total": len(self.workers) + tb,
            "successful": len(results) + tb,
            "failed": len(failures),
        }
        if failures:
            header["failures"] = failures
        return {"trace_id": trace_id, "_nodes": header, "spans": spans}

    def wait_for(
        self,
        predicate: Callable[[], bool],
        timeout_s: float = 30.0,
        interval_s: float = 0.1,
        what: str = "condition",
    ) -> None:
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                if predicate():
                    return
            except (ConnectTransportError, RemoteActionError):
                pass  # mid-failover flakes: keep polling
            if time.monotonic() >= deadline:
                raise ProcClusterUnavailableError(
                    f"timed out after {timeout_s}s waiting for {what}"
                )
            time.sleep(interval_s)

    # ------------------------------------------------------------ teardown

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        try:
            for node_id in self.workers:
                try:
                    self._ctl.send(
                        "_ctl", node_id, "_shutdown", {}, timeout_s=2.0
                    )
                except (ConnectTransportError, RemoteActionError):
                    pass  # already dead
            with self._lock:
                procs = dict(self._procs)
            deadline = time.monotonic() + 10.0
            for node_id, proc in procs.items():
                proc.join(timeout=max(0.1, deadline - time.monotonic()))
                if proc.is_alive():
                    os.kill(proc.pid, signal.SIGKILL)
                    proc.join(timeout=5)
            if self._stepper is not None:
                self._stepper.join(timeout=2)
        finally:
            # Child reaping must NEVER leak the supervisor's sockets: the
            # tiebreaker endpoint and the `_ctl` listener close even when
            # a join/kill above throws (a leaked `_ctl` listener holds
            # its port and fd for the supervisor's lifetime).
            try:
                if self._local_node is not None:
                    self._local_node.close()
                    self._tb_transport.close()
            finally:
                self._ctl.close()
