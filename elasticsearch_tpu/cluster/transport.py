"""In-memory node-to-node transport with fault injection.

The host-level RPC layer of the cluster (SURVEY §2.4's control plane): the
reference moves cluster state, replicated writes, and peer recovery over
transport-netty4 TCP channels; on a TPU pod the data plane is ICI
collectives (parallel/sharded.py) and only this control plane crosses
hosts. The in-memory hub is the test-cluster form — the reference's
MockTransportService pattern (test/framework .../MockTransportService) —
with the same interception points (disconnect, partition, drop-by-action,
delay) a TCP implementation faults on, so replication/failover logic
is exercised against real message loss without real sockets. The real-
socket implementation of the SAME surface lives in cluster/tcp_transport.py
(TcpTransportHub / TcpTransport); both share `TransportIntercepts` so a
chaos schedule written against one transport runs unchanged on the other.

Every send is bounded: `send` carries a per-call deadline (default
`ESTPU_TRANSPORT_TIMEOUT_S`, 10s) and raises ConnectTransportError on
expiry — an injected `delay` or a wedged remote handler can never block a
caller forever. This is the same contract the TCP transport honors with
socket timeouts, so the gateway's retry loop sees one timeout semantics
across both transports.
"""

from __future__ import annotations

import fnmatch
import os
import threading
import time
from typing import Any, Callable

from ..faults import fault_point
from ..obs.tracing import TRACER

# Per-send deadline shared by BOTH transports (the in-memory hub joins the
# handler thread against it; the TCP transport drives socket timeouts from
# it). <= 0 disables the bound (escape hatch for debugging).
DEFAULT_TIMEOUT_S = float(os.environ.get("ESTPU_TRANSPORT_TIMEOUT_S", "10") or 10)


class ConnectTransportError(Exception):
    """Peer unreachable (dead node, partition, injected disconnect) or a
    send that exceeded its deadline without a response."""


class RemoteActionError(Exception):
    """The remote handler raised; carries the remote error text plus the
    remote exception's type name in `remote_type` so callers can react to
    specific failures (e.g. stale-primary-term rejections) without
    fragile message matching."""

    def __init__(self, message: str, remote_type: str = ""):
        super().__init__(message)
        self.remote_type = remote_type


class TransportIntercepts:
    """Sender-side interception state: the MockTransportService surface
    (disconnect pairs, partition groups, drop-by-action, added latency)
    shared by the in-memory hub and the TCP transport. In a multi-process
    cluster every worker holds its own copy and the supervisor broadcasts
    updates over a control action, so a partition applies symmetrically at
    each node's real socket layer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._partitions: list[set[str]] = []  # disjoint reachability groups
        self._disconnected: set[frozenset] = set()  # unordered pairs
        self._dropped_actions: list[tuple[str, str, str]] = []  # from,to,pat
        self._delays: list[tuple[str, str, float]] = []  # from,to,seconds
        self.delay_s = 0.0

    def disconnect(self, a: str, b: str) -> None:
        with self._lock:
            self._disconnected.add(frozenset((a, b)))

    def reconnect(self, a: str, b: str) -> None:
        with self._lock:
            self._disconnected.discard(frozenset((a, b)))

    def partition(self, *groups: set[str]) -> None:
        """Only nodes within the same group can talk."""
        with self._lock:
            self._partitions = [set(g) for g in groups]

    def heal_partition(self) -> None:
        with self._lock:
            self._partitions = []

    def drop_action(self, from_id: str, to_id: str, pattern: str) -> None:
        """Drop matching requests (fnmatch on action; '*' wildcards ids)."""
        with self._lock:
            self._dropped_actions.append((from_id, to_id, pattern))

    def clear_drops(self) -> None:
        with self._lock:
            self._dropped_actions = []

    def set_delay(
        self, seconds: float, from_id: str = "*", to_id: str = "*"
    ) -> None:
        """Add latency to matching sends ('*' wildcards ids). The default
        all-pairs form keeps the historical global knob; a targeted form
        (e.g. ``set_delay(0.5, to_id="node-1")``) models ONE slow/wedged
        peer — the brownout shape — without touching healthy paths.
        ``set_delay(0)`` with wildcards clears everything; ``seconds=0``
        on a targeted pair clears just that pair's rules."""
        with self._lock:
            if from_id == "*" and to_id == "*":
                self.delay_s = seconds
                if not seconds:
                    self._delays = []
                return
            self._delays = [
                (f, t, s)
                for f, t, s in self._delays
                if (f, t) != (from_id, to_id)
            ]
            if seconds:
                self._delays.append((from_id, to_id, float(seconds)))

    def delay_for(self, from_id: str, to_id: str) -> float:
        """Effective injected latency for one send: the global delay or
        the largest matching targeted rule, whichever is worse."""
        with self._lock:
            delay = self.delay_s
            for f, t, s in self._delays:
                if fnmatch.fnmatch(from_id, f) and fnmatch.fnmatch(to_id, t):
                    delay = max(delay, s)
            return delay

    def reachable(self, a: str, b: str) -> bool:
        with self._lock:
            if frozenset((a, b)) in self._disconnected:
                return False
            for group in self._partitions:
                if (a in group) != (b in group):
                    return False
            return True

    def dropped(self, from_id: str, to_id: str, action: str) -> bool:
        with self._lock:
            drops = list(self._dropped_actions)
        for f, t, pat in drops:
            if (
                fnmatch.fnmatch(from_id, f)
                and fnmatch.fnmatch(to_id, t)
                and fnmatch.fnmatch(action, pat)
            ):
                return True
        return False

    def preflight(
        self,
        from_id: str,
        to_id: str,
        action: str,
        deadline: float | None,
        timeout_s: float,
        on_timeout: Callable[[], None],
    ) -> None:
        """The sender-side gate both transports run before touching the
        wire — ONE implementation so the interception semantics (and the
        delay-vs-deadline interplay) can never diverge between them.
        Raises ConnectTransportError for partitions/disconnects, dropped
        actions, and injected delays that blow the send deadline
        (counting via on_timeout first); sleeps surviving delays."""
        if not self.reachable(from_id, to_id):
            raise ConnectTransportError(
                f"[{to_id}] unreachable from [{from_id}]"
            )
        if self.dropped(from_id, to_id, action):
            raise ConnectTransportError(
                f"[{action}] {from_id}->{to_id} dropped by interceptor"
            )
        delay = self.delay_for(from_id, to_id)
        if delay:
            if deadline is not None and time.monotonic() + delay > deadline:
                # The injected latency alone blows the budget: honor the
                # deadline, not the sleep.
                time.sleep(max(0.0, deadline - time.monotonic()))
                on_timeout()
                raise ConnectTransportError(
                    f"[{action}] {from_id}->{to_id} timed out after "
                    f"{timeout_s}s (injected delay)"
                )
            time.sleep(delay)

    # ------------------------------------------- control-channel transfer

    def to_json(self) -> dict:
        with self._lock:
            return {
                "partitions": [sorted(g) for g in self._partitions],
                "disconnected": [sorted(p) for p in self._disconnected],
                "drops": [list(d) for d in self._dropped_actions],
                "delays": [list(d) for d in self._delays],
                "delay_s": self.delay_s,
            }

    def load(self, data: dict) -> None:
        """Replace the whole interception state (the supervisor's
        broadcast form: every worker converges on one ruleset)."""
        with self._lock:
            self._partitions = [set(g) for g in data.get("partitions", [])]
            self._disconnected = {
                frozenset(p) for p in data.get("disconnected", [])
            }
            self._dropped_actions = [
                (d[0], d[1], d[2]) for d in data.get("drops", [])
            ]
            self._delays = [
                (d[0], d[1], float(d[2])) for d in data.get("delays", [])
            ]
            self.delay_s = float(data.get("delay_s", 0.0))


class InterceptsDelegate:
    """The hub-level fault-injection surface, delegated to
    `self.intercepts`: tests/operators interact with
    `cluster.hub.partition(...)` no matter which transport backs it."""

    intercepts: TransportIntercepts

    def disconnect(self, a: str, b: str) -> None:
        self.intercepts.disconnect(a, b)

    def reconnect(self, a: str, b: str) -> None:
        self.intercepts.reconnect(a, b)

    def partition(self, *groups: set[str]) -> None:
        self.intercepts.partition(*groups)

    def heal_partition(self) -> None:
        self.intercepts.heal_partition()

    def drop_action(self, from_id: str, to_id: str, pattern: str) -> None:
        self.intercepts.drop_action(from_id, to_id, pattern)

    def clear_drops(self) -> None:
        self.intercepts.clear_drops()

    def set_delay(
        self, seconds: float, from_id: str = "*", to_id: str = "*"
    ) -> None:
        self.intercepts.set_delay(seconds, from_id, to_id)


class TransportHub(InterceptsDelegate):
    """Shared in-process switchboard for a LocalCluster's nodes."""

    def __init__(self, default_timeout_s: float | None = None):
        self._handlers: dict[str, Callable[[str, str, dict], Any]] = {}
        self._lock = threading.Lock()
        self.intercepts = TransportIntercepts()
        self.default_timeout_s = (
            DEFAULT_TIMEOUT_S if default_timeout_s is None else default_timeout_s
        )
        from ..obs.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        self._timeouts = self.metrics.counter(
            "estpu_transport_send_timeouts_total",
            "Transport sends that exceeded their per-send deadline",
            transport="hub",
        )
        # Windowed twin (health `transport` indicator input): timeouts
        # over the trailing window, not since boot.
        self._timeouts_recent = self.metrics.windowed_counter(
            "estpu_transport_events_recent",
            "Transport events over the trailing window",
            event="send_timeout",
            transport="hub",
        )

    # ------------------------------------------------------------ wiring

    def register(
        self, node_id: str, handler: Callable[[str, str, dict], Any]
    ) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._handlers.pop(node_id, None)

    # ------------------------------------------------------------- sending

    def send(
        self,
        from_id: str,
        to_id: str,
        action: str,
        payload: dict,
        timeout_s: float | None = None,
    ):
        """Synchronous request/response; raises ConnectTransportError on
        unreachable peers (and on deadline expiry) and RemoteActionError
        for remote failures.

        Trace context rides the wire: when the sender has an active span,
        the payload carries `_trace` (trace_id + parent span id) so the
        receiving node's execution parents into the caller's tree exactly
        as it would across real sockets — the receive side re-activates
        the explicit context rather than trusting thread locals."""
        timeout_s = self.default_timeout_s if timeout_s is None else timeout_s
        deadline = (
            time.monotonic() + timeout_s if timeout_s > 0 else None
        )
        with self._lock:
            handler = self._handlers.get(to_id)
        with TRACER.span(
            f"transport.{action}", from_node=from_id, to_node=to_id
        ):
            if handler is None:
                raise ConnectTransportError(
                    f"[{to_id}] unreachable from [{from_id}]"
                )
            self.intercepts.preflight(
                from_id, to_id, action, deadline, timeout_s,
                on_timeout=self._note_timeout,
            )
            # Named fault site (faults/registry.py): injectable per-action
            # drops/delays without pre-wiring hub interceptors, e.g.
            # `transport.send.shard_search`.
            fault_point(
                f"transport.send.{action}", from_node=from_id, to_node=to_id
            )
            ctx = TRACER.context()
            if ctx is not None:
                payload = dict(
                    payload, _trace={"trace_id": ctx[0], "parent": ctx[1]}
                )
            if deadline is None:
                return _invoke(handler, from_id, to_id, action, payload)
            return self._bounded_invoke(
                handler, from_id, to_id, action, payload, deadline, timeout_s
            )

    def _bounded_invoke(
        self, handler, from_id, to_id, action, payload, deadline, timeout_s
    ):
        """Run the handler on a worker thread and join against the
        deadline: a response that never comes surfaces as
        ConnectTransportError, exactly like a socket recv timeout. The
        abandoned handler may still complete its side effects — the same
        at-least-once ambiguity a real network timeout leaves behind."""
        box: dict[str, Any] = {}

        def run():
            try:
                box["result"] = handler(from_id, action, payload)
            # staticcheck: ignore[broad-except] wire boundary: the failure is carried back to the sending thread and classified there exactly like an on-thread call
            except BaseException as e:
                box["error"] = e

        worker = threading.Thread(
            target=run, daemon=True, name=f"hub-send-{action}"
        )
        worker.start()
        worker.join(max(0.0, deadline - time.monotonic()))
        if worker.is_alive():
            self._note_timeout()
            raise ConnectTransportError(
                f"[{action}] on [{to_id}] timed out after {timeout_s}s "
                f"(no response within the per-send deadline)"
            )
        if "error" in box:
            _raise_as_remote(box["error"], action, to_id)
        return box.get("result")

    def _note_timeout(self) -> None:
        self._timeouts.inc()
        self._timeouts_recent.inc()

    def alive(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self._handlers

    def stats(self) -> dict:
        with self._lock:
            registered = sorted(self._handlers)
        return {
            "kind": "hub",
            "registered": registered,
            "send_timeouts": int(self._timeouts.value),
        }


def scatter_nodes(
    node_ids,
    send: Callable[[str], Any],
    action: str,
    timeout_s: float | None,
    metrics=None,
) -> tuple[dict[str, Any], list[dict[str, str]]]:
    """Parallel scatter of one wire action over many nodes with per-node
    failure capture — the TransportNodesAction fan-in shape shared by
    `_nodes/stats`, the federated `/_metrics` scrape, trace-fragment
    collection, and hot-threads sampling.

    ``send(node_id)`` performs the transport call and is already bounded
    by its per-send deadline; a node that is dead, partitioned, or wedged
    past that deadline becomes a NAMED failure entry — never an exception
    out of the fan and never a hang (the join carries a small grace over
    the send deadline as a belt-and-braces bound). Returns
    ``(results by node id, failures [{node, type, reason}])``."""
    results: dict[str, Any] = {}
    failures: list[dict[str, str]] = []
    # Nodes whose worker outlived the join grace: their (late) outcome
    # must NOT mutate the returned dicts after the caller starts reading
    # them — the failure entry recorded at abandonment is final.
    abandoned: set[str] = set()
    out_lock = threading.Lock()
    t0 = time.monotonic()

    def fan_one(nid: str) -> None:
        try:
            result = send(nid)
        # staticcheck: ignore[broad-except] fan-in boundary: ANY per-node failure (transport, remote, local bug) must become a named failure entry — partial tolerance is the contract
        except Exception as e:
            with out_lock:
                if nid not in abandoned:
                    failures.append(
                        {
                            "node": nid,
                            "type": type(e).__name__,
                            "reason": str(e),
                        }
                    )
        else:
            with out_lock:
                if nid not in abandoned:
                    results[nid] = result

    workers = [
        threading.Thread(
            target=fan_one,
            args=(nid,),
            daemon=True,
            name=f"nodes-fan-{action}-{nid}",
        )
        for nid in node_ids
    ]
    for worker in workers:
        worker.start()
    grace = (timeout_s if timeout_s and timeout_s > 0 else 30.0) + 2.0
    deadline = time.monotonic() + grace
    for nid, worker in zip(node_ids, workers):
        worker.join(max(0.0, deadline - time.monotonic()))
        if worker.is_alive():
            with out_lock:
                abandoned.add(nid)
                if nid not in results and not any(
                    f["node"] == nid for f in failures
                ):
                    failures.append(
                        {
                            "node": nid,
                            "type": "ConnectTransportError",
                            "reason": (
                                f"[{action}] fan-in deadline exceeded "
                                f"after {grace}s"
                            ),
                        }
                    )
    if metrics is not None:
        from ..obs.metrics import NODES_FAN_LATENCY_MS_BUCKETS

        metrics.counter(
            "estpu_nodes_stats_fanouts_total",
            "Cluster-wide stats/obs scatter rounds by wire action",
            action=action,
        ).inc()
        if failures:
            metrics.counter(
                "estpu_nodes_stats_fan_failures_total",
                "Named per-node failures during stats/obs fan-in",
                action=action,
            ).inc(len(failures))
        metrics.histogram(
            "estpu_nodes_stats_fan_latency_ms",
            NODES_FAN_LATENCY_MS_BUCKETS,
            "Wall-clock fan-in latency of stats/obs scatter rounds",
        ).observe((time.monotonic() - t0) * 1e3)
    return results, failures


def _invoke(handler, from_id, to_id, action, payload):
    try:
        return handler(from_id, action, payload)
    except (ConnectTransportError, RemoteActionError):
        raise
    # staticcheck: ignore[broad-except] wire boundary: a remote handler failure must cross as RemoteActionError exactly like a real RPC (chaos parity includes injected faults)
    except Exception as e:  # remote handler failure crosses the wire
        _raise_as_remote(e, action, to_id)


def _raise_as_remote(e: BaseException, action: str, to_id: str):
    """Classify a handler failure the way the wire would: transport-shaped
    errors pass through, everything else crosses as RemoteActionError."""
    if isinstance(e, (ConnectTransportError, RemoteActionError)):
        raise e
    raise RemoteActionError(
        f"[{action}] on [{to_id}]: {e}", remote_type=type(e).__name__
    ) from e
