"""In-memory node-to-node transport with fault injection.

The host-level RPC layer of the cluster (SURVEY §2.4's control plane): the
reference moves cluster state, replicated writes, and peer recovery over
transport-netty4 TCP channels; on a TPU pod the data plane is ICI
collectives (parallel/sharded.py) and only this control plane crosses
hosts. The in-memory hub is the test-cluster form — the reference's
MockTransportService pattern (test/framework .../MockTransportService) —
with the same interception points (disconnect, partition, drop-by-action,
delay) a TCP implementation would fault on, so replication/failover logic
is exercised against real message loss without real sockets.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Any, Callable

from ..faults import fault_point
from ..obs.tracing import TRACER


class ConnectTransportError(Exception):
    """Peer unreachable (dead node, partition, injected disconnect)."""


class RemoteActionError(Exception):
    """The remote handler raised; carries the remote error text plus the
    remote exception's type name in `remote_type` so callers can react to
    specific failures (e.g. stale-primary-term rejections) without
    fragile message matching."""

    def __init__(self, message: str, remote_type: str = ""):
        super().__init__(message)
        self.remote_type = remote_type


class TransportHub:
    """Shared in-process switchboard for a LocalCluster's nodes."""

    def __init__(self):
        self._handlers: dict[str, Callable[[str, str, dict], Any]] = {}
        self._lock = threading.Lock()
        self._partitions: list[set[str]] = []  # disjoint reachability groups
        self._disconnected: set[frozenset] = set()  # unordered pairs
        self._dropped_actions: list[tuple[str, str, str]] = []  # from,to,pat
        self._delay_s = 0.0

    # ------------------------------------------------------------ wiring

    def register(
        self, node_id: str, handler: Callable[[str, str, dict], Any]
    ) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._handlers.pop(node_id, None)

    # ---------------------------------------------------- fault injection

    def disconnect(self, a: str, b: str) -> None:
        with self._lock:
            self._disconnected.add(frozenset((a, b)))

    def reconnect(self, a: str, b: str) -> None:
        with self._lock:
            self._disconnected.discard(frozenset((a, b)))

    def partition(self, *groups: set[str]) -> None:
        """Only nodes within the same group can talk."""
        with self._lock:
            self._partitions = [set(g) for g in groups]

    def heal_partition(self) -> None:
        with self._lock:
            self._partitions = []

    def drop_action(self, from_id: str, to_id: str, pattern: str) -> None:
        """Drop matching requests (fnmatch on action; '*' wildcards ids)."""
        with self._lock:
            self._dropped_actions.append((from_id, to_id, pattern))

    def clear_drops(self) -> None:
        with self._lock:
            self._dropped_actions = []

    def set_delay(self, seconds: float) -> None:
        self._delay_s = seconds

    # ------------------------------------------------------------- sending

    def _reachable(self, a: str, b: str) -> bool:
        if frozenset((a, b)) in self._disconnected:
            return False
        for group in self._partitions:
            if (a in group) != (b in group):
                return False
        return True

    def send(self, from_id: str, to_id: str, action: str, payload: dict):
        """Synchronous request/response; raises ConnectTransportError on
        unreachable peers and RemoteActionError for remote failures.

        Trace context rides the wire: when the sender has an active span,
        the payload carries `_trace` (trace_id + parent span id) so the
        receiving node's execution parents into the caller's tree exactly
        as it would across real sockets — the receive side re-activates
        the explicit context rather than trusting thread locals."""
        with self._lock:
            handler = self._handlers.get(to_id)
            reachable = self._reachable(from_id, to_id)
            drops = list(self._dropped_actions)
        with TRACER.span(
            f"transport.{action}", from_node=from_id, to_node=to_id
        ):
            if handler is None or not reachable:
                raise ConnectTransportError(
                    f"[{to_id}] unreachable from [{from_id}]"
                )
            for f, t, pat in drops:
                if (
                    fnmatch.fnmatch(from_id, f)
                    and fnmatch.fnmatch(to_id, t)
                    and fnmatch.fnmatch(action, pat)
                ):
                    raise ConnectTransportError(
                        f"[{action}] {from_id}->{to_id} dropped by interceptor"
                    )
            if self._delay_s:
                time.sleep(self._delay_s)
            # Named fault site (faults/registry.py): injectable per-action
            # drops/delays without pre-wiring hub interceptors, e.g.
            # `transport.send.shard_search`.
            fault_point(
                f"transport.send.{action}", from_node=from_id, to_node=to_id
            )
            ctx = TRACER.context()
            if ctx is not None:
                payload = dict(
                    payload, _trace={"trace_id": ctx[0], "parent": ctx[1]}
                )
            try:
                return handler(from_id, action, payload)
            except (ConnectTransportError, RemoteActionError):
                raise
            # staticcheck: ignore[broad-except] wire boundary: a remote handler failure must cross as RemoteActionError exactly like a real RPC (chaos parity includes injected faults)
            except Exception as e:  # remote handler failure crosses the wire
                raise RemoteActionError(
                    f"[{action}] on [{to_id}]: {e}",
                    remote_type=type(e).__name__,
                ) from e

    def alive(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self._handlers
