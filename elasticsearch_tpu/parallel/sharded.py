"""Sharded search over a device mesh: the cluster, in one XLA program.

The reference scales search by scattering per-shard QUERY requests over TCP
and reducing on a coordinator (AbstractSearchAsyncAction.java:280 fan-out;
SearchPhaseController.java:398 reduce; QueryPhaseResultConsumer incremental
merge). Here the entire scatter-gather collapses into a single SPMD program:

- every shard's tiled postings live on its own device (leading `shard` mesh
  axis, `jax.sharding.NamedSharding`);
- one `shard_map` program scores all shards simultaneously, takes each
  shard's local top-k, and merges via `jax.lax.all_gather` over the ICI —
  the coordinator reduce becomes a collective;
- total-hit counts reduce with `psum`.

Global term statistics: per-shard IDF would make scores depend on routing
(the reference has the same artifact and fixes it with the DFS phase,
search/dfs/DfsPhase.java:31). `ShardedIndex.field_stats` aggregates
statistics across shards at plan time — the DFS phase equivalent, free on
the host because the coordinator owns all term dictionaries here.

Tie-breaking: the merged flat top-k favors lower (shard, local-rank) on
equal scores, which is exactly (shard index, doc id) order — the same
contract as the reference's mergeTopDocs shard-order tie-break.

Doc addressing: global doc = shard * padded_size + local, reversible on the
host for the fetch phase (`locate`).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field as dc_field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..index.mapping import Mappings
from ..index.segment import FieldIndex, Segment, SegmentBuilder
from ..index.tiles import TILE, pack_segment, tile_doc_bounds
from ..obs.metrics import timed_launch
from ..ops.bm25 import BM25Params
from ..ops.bm25_device import (
    NEG_INF,
    _eval_node,
    _sparse_inner,
    segment_tree,
    supports_sparse,
)
from ..query.compile import (
    CompiledQuery,
    Compiler,
    FieldStats,
    SpecUnifyError,
    aggregate_field_stats,
    equalize_compiled,
    pad_arrays_to_spec,
    unify_specs,
)
from ..query.dsl import Query
from .routing import shard_for_id


def _shard_map(body, mesh: Mesh, in_specs, out_specs):
    """`jax.shard_map` (public since 0.6, kw `check_vma`) or the older
    `jax.experimental.shard_map.shard_map` (kw `check_rep`) — the mesh
    serving path must work on both; replication checking is off either way
    (the reduce mixes per-shard and replicated values)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    return _legacy_shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=False,
    )


def _empty_field(name: str, num_docs: int, has_norms: bool) -> FieldIndex:
    return FieldIndex(
        name=name,
        terms={},
        df=np.zeros(0, dtype=np.int32),
        offsets=np.zeros(1, dtype=np.int64),
        doc_ids=np.zeros(0, dtype=np.int32),
        tfs=np.zeros(0, dtype=np.float32),
        norm_bytes=np.zeros(num_docs, dtype=np.uint8),
        doc_count=0,
        sum_total_tf=0,
        has_norms=has_norms,
        present=np.zeros(num_docs, dtype=bool),
        # Text fields carry (empty) position planes so every shard's pytree
        # has the same structure for the mesh stack.
        pos_offsets=np.zeros(1, dtype=np.int64) if has_norms else None,
        positions=np.zeros(0, dtype=np.int32) if has_norms else None,
    )


def union_schema(
    segments: list[Segment],
) -> tuple[dict[str, bool], set[str], dict[str, int]]:
    """Cross-shard union of (field -> has_norms, doc-value names,
    vector field -> dim) — the single definition of the uniform-schema
    invariant every stacked mesh pytree relies on."""
    fields: dict[str, bool] = {}
    dv: set[str] = set()
    vec: dict[str, int] = {}
    for seg in segments:
        for name, fld in seg.fields.items():
            fields[name] = fld.has_norms
        dv.update(seg.doc_values)
        for name, mat in seg.vectors.items():
            vec[name] = mat.shape[1]
    return fields, dv, vec


def fill_union_schema(
    seg: Segment,
    fields: dict[str, bool],
    dv: set[str],
    vec: dict[str, int],
) -> Segment:
    """Shallow-copied segment carrying the cross-shard union schema
    (missing fields empty, doc-value columns NaN, vector columns zero) so
    every shard's packed pytree has identical structure.

    Returns a COPY with fresh dicts — never mutates `seg`, which callers
    (the mesh serving view, mesh_snapshot) may share with still-serving
    snapshots on other threads.
    """
    from dataclasses import replace as dc_replace

    new_fields = dict(seg.fields)
    for name, has_norms in fields.items():
        if name not in new_fields:
            new_fields[name] = _empty_field(name, seg.num_docs, has_norms)
    new_dv = dict(seg.doc_values)
    for name in dv:
        if name not in new_dv:
            new_dv[name] = np.full(seg.num_docs, np.nan)
    new_vec = dict(seg.vectors)
    for name, dim in vec.items():
        if name not in new_vec:
            new_vec[name] = np.zeros((seg.num_docs, dim), dtype=np.float32)
    return dc_replace(
        seg, fields=new_fields, doc_values=new_dv, vectors=new_vec
    )


_SHARDED_UIDS = itertools.count(1)


@dataclass
class ShardedIndex:
    """N shards stacked on a leading mesh axis, searchable as one program."""

    mesh: Mesh
    axis: str
    mappings: Mappings
    segments: list[Segment]  # host-side, for stats + fetch phase
    seg_stacked: Any  # pytree: every leaf [n_shards, ...], device-sharded
    docs_per_shard: int  # padded per-shard doc capacity (global id stride)
    params: BM25Params
    # index.filter_cache.FilterCache: when set, `search` substitutes
    # cacheable filter-context clauses with [S, N] stacked mask planes
    # (computed once via compute_filter_mask_stacked, keyed on this
    # index's process-unique uid — shards are immutable, so planes never
    # go stale; the cache's LRU/HBM budget still bounds residency).
    filter_cache: Any = None
    # Cache-key scope + generation override (mesh_serving.MeshView): a
    # refresh-tracking view sets scope to its engines' uid tuple and
    # generation to their monotonic sum, so snapshot rebuilds invalidate
    # planes via the ordinary stale-generation purge and the per-index
    # `_cache/clear` can address them. None = the immutable default
    # (this instance's process-unique uid, generation pinned 0).
    cache_scope: Any = None
    cache_generation: int = 0
    # obs.metrics.DeviceInstruments: per-launch timing (queue/execute
    # split + retrace-census attribution) for direct mesh searches.
    # None = uninstrumented (the MeshView serving path wraps its own
    # launches in MeshView.serve instead).
    instruments: Any = None
    _stats_cache: dict[str, FieldStats] | None = None
    _id_indexes: list[dict[str, int] | None] | None = None
    # Memoized per-(shard, field) tile doc-id bounds for plan-time
    # conjunction range pruning (computed once; shards are immutable).
    _tile_bounds: dict | None = None
    _cache_uid: int = dc_field(
        default_factory=lambda: next(_SHARDED_UIDS)
    )

    def _field_tile_bounds(self, shard: int, name: str):
        if self._tile_bounds is None:
            self._tile_bounds = {}
        key = (shard, name)
        if key not in self._tile_bounds:
            fld = self.segments[shard].fields.get(name)
            if fld is None or not len(fld.doc_ids):
                self._tile_bounds[key] = (None, None)
            else:
                self._tile_bounds[key] = tile_doc_bounds(
                    fld.doc_ids, self.segments[shard].num_docs
                )
        return self._tile_bounds[key]

    def _id_index(self, shard: int) -> dict[str, int]:
        """Memoized _id -> local map per shard (the index is an immutable
        snapshot, so building it once per shard suffices)."""
        if self._id_indexes is None:
            self._id_indexes = [None] * len(self.segments)
        if self._id_indexes[shard] is None:
            self._id_indexes[shard] = {
                d: i for i, d in enumerate(self.segments[shard].ids)
            }
        return self._id_indexes[shard]

    @classmethod
    def from_docs(
        cls,
        docs: list[tuple[str, dict]],
        mappings: Mappings,
        mesh: Mesh,
        axis: str = "shard",
        params: BM25Params = BM25Params(),
    ) -> "ShardedIndex":
        """Route (id, source) docs to shards and build the stacked index."""
        n_shards = mesh.shape[axis]
        builders = [SegmentBuilder(mappings) for _ in range(n_shards)]
        for doc_id, source in docs:
            builders[shard_for_id(doc_id, n_shards)].add(source, doc_id)
        return cls.from_segments(
            [b.build() for b in builders], mappings, mesh, axis, params
        )

    @classmethod
    def from_segments(
        cls,
        segments: list[Segment],
        mappings: Mappings,
        mesh: Mesh,
        axis: str = "shard",
        params: BM25Params = BM25Params(),
    ) -> "ShardedIndex":
        n_shards = mesh.shape[axis]
        if len(segments) != n_shards:
            raise ValueError(
                f"{len(segments)} segments for a {n_shards}-shard mesh axis"
            )
        if any(s.nested for s in segments):
            raise ValueError(
                "nested blocks are not mesh-stackable yet; serve nested "
                "indices through the host-loop coordinator"
            )
        # Uniform schema: every shard carries the union of fields/columns.
        all_fields, all_dv, all_vec = union_schema(segments)
        n_pad = max((s.num_docs for s in segments), default=0)
        n_pad = max(n_pad, 1)
        min_tiles: dict[str, int] = {}
        pos_min_tiles: dict[str, int] = {}
        for seg in segments:
            for name in all_fields:
                fld = seg.fields.get(name)
                postings = len(fld.doc_ids) if fld is not None else 0
                tiles = postings // TILE + 2  # data tiles + sentinel tile
                min_tiles[name] = max(min_tiles.get(name, 0), tiles)
                npos = (
                    len(fld.positions)
                    if fld is not None and fld.positions is not None
                    else 0
                )
                if all_fields[name]:  # text field: position planes stack too
                    pos_min_tiles[name] = max(
                        pos_min_tiles.get(name, 0), npos // TILE + 2
                    )
        # Global (cross-shard) avgdl so precomputed impacts match the DFS
        # statistics scope the compiler will score with.
        global_stats = aggregate_field_stats(segments)
        global_avgdl = {name: s.avgdl for name, s in global_stats.items()}
        trees = []
        segments = [
            fill_union_schema(seg, all_fields, all_dv, all_vec)
            for seg in segments
        ]
        for seg in segments:
            dev = pack_segment(
                seg,
                pad_docs_to=n_pad,
                field_min_tiles=min_tiles,
                field_avgdl=global_avgdl,
                k1=params.k1,
                b=params.b,
                field_pos_min_tiles=pos_min_tiles,
            )
            trees.append(segment_tree(dev))
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
        sharding = NamedSharding(mesh, P(axis))
        stacked = jax.tree.map(
            lambda x: jax.device_put(x, sharding), stacked
        )
        return cls(
            mesh=mesh,
            axis=axis,
            mappings=mappings,
            segments=segments,
            seg_stacked=stacked,
            docs_per_shard=n_pad,
            params=params,
        )

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[self.axis]

    def field_stats(self) -> dict[str, FieldStats]:
        """Cross-shard statistics: the DFS phase, computed at plan time.

        Cached — shards are immutable once the index is built."""
        if self._stats_cache is None:
            self._stats_cache = aggregate_field_stats(self.segments)
        return self._stats_cache

    def _tn_avgdl(self, shard: int, field: str, fstats) -> float:
        """Statistics scope the packed tn (impact) planes are valid for.

        The base class packs at build time with the same aggregated stats
        `compile` scores with, so the fast precomputed-impact kernel always
        applies. `MeshIndex` (parallel/mesh_serving.py) overrides this with
        the per-shard PACK-TIME avgdl so the compiler falls back to the
        norm-cache gather kernel whenever statistics have drifted since the
        shard was last uploaded — stale tn planes are then simply unused.
        """
        return float(fstats.avgdl) if fstats else 1.0

    def shard_compiler(self, shard: int, nt_floor: int = 1) -> Compiler:
        """Host-side planning view for one shard over the same offsets the
        device sees — the per-shard Compiler behind `compile`, also used
        by the mesh serving path to lower aggregation plans (filter-agg
        sub-queries) into shard-uniform specs."""
        stats = self.field_stats()
        seg = self.segments[shard]
        fields = {}
        for name, fld in seg.fields.items():
            postings = len(fld.doc_ids)
            nt = postings // TILE + 2
            fstats = stats.get(name)
            b_lo, b_hi = self._field_tile_bounds(shard, name)
            fields[name] = _PlanField(
                tile_doc_lo=b_lo,
                tile_doc_hi=b_hi,
                name=name,
                terms=fld.terms,
                df=fld.df,
                offsets=fld.offsets,
                doc_count=fld.doc_count,
                sum_total_tf=fld.sum_total_tf,
                has_norms=fld.has_norms,
                num_tiles_=max(nt, 0),
                # Impacts validity scope: see _tn_avgdl. When it matches
                # the stats avgdl the fast (precomputed-impact) kernel
                # applies; otherwise the gather kernel recomputes
                # impacts from tf + norm bytes with the current stats.
                tn_avgdl=self._tn_avgdl(shard, name, fstats),
                tn_k1=self.params.k1,
                tn_b=self.params.b,
                pos_offsets=fld.pos_offsets,
                pos_num_tiles_=(
                    len(fld.positions) // TILE + 2
                    if fld.positions is not None
                    else 0
                ),
            )
        return Compiler(
            fields=fields,
            doc_values={name: None for name in seg.doc_values},
            mappings=self.mappings,
            params=self.params,
            stats=stats,
            nt_floor=nt_floor,
            id_index=lambda s=shard: self._id_index(s),
        )

    def compile(self, query: Query, nt_floor: int = 1) -> CompiledQuery:
        """Compile per shard with uniform buckets; stack arrays on axis 0."""
        first = [
            self.shard_compiler(i, nt_floor).compile(query)
            for i in range(len(self.segments))
        ]
        specs_match = len({c.spec for c in first}) == 1
        if not specs_match:
            # Per-node-position equalization: each clause's bucket rises
            # only to ITS max across shards (array padding, no recompile).
            # The old single group-wide nt_floor let one fat clause (a
            # high-df filter term) inflate every other clause's worklist
            # — the BENCH_r05 cfg3 sort blow-up.
            try:
                first = equalize_compiled(first)
            except SpecUnifyError:
                nt_max = max(_max_nt(c.spec) for c in first)
                first = [
                    self.shard_compiler(i, nt_max).compile(query)
                    for i in range(len(self.segments))
                ]
            if len({c.spec for c in first}) != 1:
                raise AssertionError(
                    "sharded compile produced divergent specs even with a "
                    "common worklist floor"
                )
        spec = first[0].spec
        arrays = jax.tree.map(lambda *xs: np.stack(xs), *[c.arrays for c in first])
        return CompiledQuery(spec=spec, arrays=arrays)

    def compile_batch(self, queries: list[Query]) -> CompiledQuery:
        """Compile a batch of same-shape queries; arrays get a leading Q axis.

        All queries must lower to the same operator-tree structure; shape
        buckets (term count, tile count) are equalized automatically by
        recompiling with the batch-max floors — the batched executor then
        runs one program for the whole batch.
        """
        compiled = [self.compile(q) for q in queries]
        specs = {c.spec for c in compiled}
        if len(specs) != 1:
            try:
                compiled = equalize_compiled(compiled)
            except SpecUnifyError:
                pass
            specs = {c.spec for c in compiled}
        if len(specs) != 1:
            raise ValueError(
                "batched queries must share one compiled operator tree; got "
                f"{len(specs)} distinct specs after bucket equalization"
            )
        arrays = jax.tree.map(
            lambda *xs: np.stack(xs), *[c.arrays for c in compiled]
        )
        return CompiledQuery(spec=compiled[0].spec, arrays=arrays)

    def compile_batch_buckets(
        self, queries: list[Query]
    ) -> list[tuple[CompiledQuery, list[int]]]:
        """Adaptive worklist bucketing for a query batch: instead of ONE
        launch padded to the batch-wide maximum (whose padding made cfg3's
        batched execution slower than sequential, BENCH_r05), queries
        group into pow-2 sub-buckets — each query padded only to its own
        bucket, one launch per bucket. A smaller group is merged into a
        larger bucket only when the padding it would pay costs less than
        the launch it saves (exec/cost.coalesce_wins). Returns
        [(batched CompiledQuery, query positions)] covering all queries.
        """
        from ..exec.batcher import plan_spec_buckets

        compiled = [self.compile(q) for q in queries]
        by_spec: dict[tuple, list[int]] = {}
        for pos, c in enumerate(compiled):
            by_spec.setdefault(c.spec, []).append(pos)
        buckets = plan_spec_buckets(
            list(by_spec.items()), n_shards=self.n_shards
        )
        out: list[tuple[CompiledQuery, list[int]]] = []
        for bucket_specs in buckets:
            positions = [p for s in bucket_specs for p in by_spec[s]]
            target = unify_specs(list(bucket_specs))
            arrays = jax.tree.map(
                lambda *xs: np.stack(xs),
                *[
                    pad_arrays_to_spec(
                        compiled[p].spec, target, compiled[p].arrays
                    )
                    for p in positions
                ],
            )
            out.append((CompiledQuery(spec=target, arrays=arrays), positions))
        return out

    def search_batch(self, queries: list[Query], k: int, batch_axis: str):
        """Batched sharded search over a 2D (batch × shard) mesh."""
        compiled = self.compile_batch(queries)
        return sharded_execute_batch(
            self.mesh,
            self.axis,
            batch_axis,
            self.seg_stacked,
            compiled.arrays,
            compiled.spec,
            k,
            self.docs_per_shard,
        )

    def locate(self, global_doc: int) -> tuple[int, int]:
        """global doc id -> (shard, local doc id) for the fetch phase."""
        return divmod(int(global_doc), self.docs_per_shard)

    def _apply_filter_cache(
        self, query: Query, compiled: CompiledQuery, record: bool = True,
        entries: list | None = None,
    ):
        """Mesh-path filter cache: substitute [S, N] stacked mask planes
        for cacheable top-level filter clauses. The planes ride the seg
        pytree (P(axis)-sharded like every other plane), so the shard_map
        body reads its own shard's row — bit-identical to recomputing the
        clause in-program. `record=False` skips the admission sighting
        (MeshView.serve passes it: the coordinator already recorded the
        request, and an execute-failure fallback to the host loop must
        not leave a second sighting behind)."""
        from ..index.filter_cache import (
            apply_cached_masks,
            record_filter_usage,
        )
        from ..ops.bm25_device import compute_filter_mask_stacked

        cache = self.filter_cache
        if entries is None:
            entries = record_filter_usage(cache, query, record=record)
        if not entries:
            return compiled, {}

        def build(child_spec, child_arrays, _norm):
            plane = compute_filter_mask_stacked(
                self.seg_stacked, child_spec, child_arrays
            )
            plane = jax.device_put(
                plane, NamedSharding(self.mesh, P(self.axis))
            )
            return plane, int(plane.nbytes)

        scope = (
            self.cache_scope
            if self.cache_scope is not None
            else ("sharded", self._cache_uid)
        )
        prefix = (scope, int(self.cache_generation), 0)
        compiled, masks, _reused = apply_cached_masks(
            cache, prefix, query, compiled, build,
            const_fill=lambda: {
                "boost": np.zeros(self.n_shards, dtype=np.float32)
            },
            entries=entries,
        )
        return compiled, masks

    def search(self, query: Query, k: int = 10):
        """One-call sharded search: (scores f32[k'], global_ids, total)."""
        compiled = self.compile(query)
        seg = self.seg_stacked
        if self.filter_cache is not None:
            compiled, masks = self._apply_filter_cache(query, compiled)
            if masks:
                seg = {**self.seg_stacked, "masks": masks}
        with timed_launch(
            self.instruments,
            "mesh_spmd",
            (compiled.spec, k, "sharded_direct"),
            "mesh_spmd",
        ) as tl:
            scores, ids, total = tl.dispatched(
                sharded_execute(
                    self.mesh,
                    self.axis,
                    seg,
                    compiled.arrays,
                    compiled.spec,
                    k,
                    self.docs_per_shard,
                )
            )
        scores, ids = np.asarray(scores), np.asarray(ids)
        n = min(k, int(total))
        return scores[:n], ids[:n], int(total)


@dataclass
class _PlanField:
    """Host-only planning stand-in for DeviceField (term dict + spans)."""

    name: str
    terms: dict
    df: Any
    offsets: Any
    doc_count: int
    sum_total_tf: int
    has_norms: bool
    num_tiles_: int
    tn_avgdl: float = -1.0
    tn_k1: float = 1.2
    tn_b: float = 0.75
    pos_offsets: Any = None  # int64[P+1] host copy (phrase planning)
    pos_num_tiles_: int = 0
    # Per-tile doc-id extrema (tiles.tile_doc_bounds), for plan-time
    # conjunction range pruning; None disables it.
    tile_doc_lo: Any = None
    tile_doc_hi: Any = None

    @property
    def avgdl(self) -> float:
        if self.doc_count == 0:
            return 1.0
        return self.sum_total_tf / self.doc_count

    @property
    def pad_tile(self) -> int:
        return self.num_tiles_ - 1

    @property
    def pos_pad_tile(self) -> int:
        return self.pos_num_tiles_ - 1

    def term_span(self, term: str) -> tuple[int, int]:
        tid = self.terms.get(term)
        if tid is None:
            return (0, 0)
        return int(self.offsets[tid]), int(self.offsets[tid + 1])

    def term_pos_span(self, term: str) -> tuple[int, int]:
        tid = self.terms.get(term)
        if tid is None or self.pos_offsets is None:
            return (0, 0)
        return (
            int(self.pos_offsets[self.offsets[tid]]),
            int(self.pos_offsets[self.offsets[tid + 1]]),
        )

    def term_df(self, term: str) -> int:
        tid = self.terms.get(term)
        if tid is None:
            return 0
        return int(self.df[tid])


def _max_nt(spec: tuple) -> int:
    """Largest worklist bucket anywhere in a compiled spec."""
    kind = spec[0]
    if kind in ("terms", "terms_const", "terms_gather", "phrase",
                "span_near", "span_not"):
        return spec[2]
    if kind == "doc_set":
        return spec[1]
    if kind in ("const", "script"):
        return _max_nt(spec[1])
    if kind == "nested":
        return _max_nt(spec[2])
    if kind == "boosting":
        return max(_max_nt(spec[1]), _max_nt(spec[2]))
    if kind == "terms_set":
        return max(
            _max_nt(spec[1]),
            max((_max_nt(c) for c in spec[2]), default=1),
        )
    if kind == "function_score":
        out = _max_nt(spec[1])
        for fil in spec[3]:
            if fil is not None:
                out = max(out, _max_nt(fil))
        return out
    if kind == "dismax":
        return max((_max_nt(c) for c in spec[1]), default=1)
    if kind == "bool":
        out = 1
        for group in spec[1:5]:
            for child in group:
                out = max(out, _max_nt(child))
        return out
    return 1


@partial(
    jax.jit, static_argnames=("mesh", "axis", "spec", "k", "docs_per_shard")
)
def sharded_execute(
    mesh: Mesh, axis: str, seg_stacked, arrays_stacked, spec, k: int, docs_per_shard: int
):
    """SPMD query: per-shard score + top-k, all-gather merge, psum totals.

    Replaces the reference's transport-level scatter/gather + coordinator
    reduce with in-program collectives over ICI (SURVEY §2.3 row 3).
    Returns replicated (scores f32[k], global ids i32[k], total i32[]).
    """

    def body(seg, arrays):
        seg = jax.tree.map(lambda x: x[0], seg)
        arrays = jax.tree.map(lambda x: x[0], arrays)
        live = seg["live"]
        n = live.shape[0]
        kk = min(k, n)
        if supports_sparse(spec):
            # Candidate-centric kernel: no [N] score plane, no dense
            # top-k — the same fast path single-chip serving uses.
            local_s, local_i, count = _sparse_inner(seg, spec, arrays, kk)
        else:
            scores, matched = _eval_node(spec, arrays, seg, n)
            eligible = matched & live
            masked = jnp.where(eligible, scores, jnp.float32(NEG_INF))
            local_s, local_i = jax.lax.top_k(masked, kk)
            count = jnp.sum(eligible, dtype=jnp.int32)
        shard_id = jax.lax.axis_index(axis)
        global_i = shard_id.astype(jnp.int32) * docs_per_shard + local_i.astype(
            jnp.int32
        )
        all_s = jax.lax.all_gather(local_s, axis)  # [S, kk]
        all_i = jax.lax.all_gather(global_i, axis)
        flat_s = all_s.reshape(-1)
        flat_i = all_i.reshape(-1)
        # Merge to min(k, S*kk), not kk: when k exceeds docs_per_shard the
        # union across shards can still fill k hits (ES returns
        # min(size, total) hits; the host trims by the psum'd total).
        top_s, idx = jax.lax.top_k(flat_s, min(k, flat_s.shape[0]))
        top_i = flat_i[idx]
        total = jax.lax.psum(count, axis)
        return top_s, top_i, total

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(), P(), P()),
    )(seg_stacked, arrays_stacked)


@partial(
    jax.jit,
    static_argnames=(
        "mesh", "axis", "spec", "k", "docs_per_shard", "sort_field",
        "sort_desc", "missing_first", "has_after", "aggs_spec",
    ),
)
def sharded_execute_request(
    mesh: Mesh,
    axis: str,
    seg_stacked,
    arrays_stacked,
    spec,
    k: int,
    docs_per_shard: int,
    sort_field: str | None = None,
    sort_desc: bool = False,
    missing_first: bool = False,
    has_after: bool = False,
    after_key=0.0,
    after_doc=0,
    aggs_spec: tuple | None = None,
    aggs_arrays_stacked=(),
):
    """One shard_map launch serving a full query phase: scoring, sorted or
    score-ordered top-k with search_after cursor masking, psum'd totals,
    AND the aggregation planes — the whole coordinator reduce as in-program
    collectives (SearchPhaseController.java:477 / FieldSortBuilder merged
    into the XLA program).

    - Field sorts rank by the transformed ascending (sort key, shard, doc)
      composite: keys via ops.bm25_device.sort_key_plane (desc negation,
      missing pinned first/last), the (shard, doc) tiebreak implicit in
      jax.lax.top_k's stable lower-flat-index-first ordering over the
      all-gathered [shard, k] key planes — bit-identical hit order to the
      host-loop FieldSortBuilder-style merge.
    - search_after applies as a key-range mask BEFORE the local top-k (the
      next page may lie beyond a shard's uncursored top-k). `after_doc` is
      mesh-global (shard * docs_per_shard + local); key-only public
      cursors pass n_shards * docs_per_shard so key ties never qualify.
    - Aggregations evaluate off the shared eligibility mask exactly like
      the single-segment program (ops/aggs_device.execute_aggs); integer
      count planes (histogram/range buckets, filter-family doc_counts) are
      psum-combined IN PROGRAM (exact — int addition is grouping-free),
      while per-shard planes (masks for the f64-exact metric finish,
      keyword ordinal counts) come back stacked [S, ...] from the same
      launch for the host fold.

    Returns (merge keys f32[k'] ascending, sort values f32[k'] (raw column
    values / scores), global ids i32[k'], total i32[], n_after i32[],
    agg results pytree with leading shard axis).
    """
    from ..ops.aggs_device import _eval_agg, mesh_combine

    def body(seg, arrays, agg_arrays, a_key, a_doc):
        seg = jax.tree.map(lambda x: x[0], seg)
        arrays = jax.tree.map(lambda x: x[0], arrays)
        agg_arrays = jax.tree.map(lambda x: x[0], agg_arrays)
        live = seg["live"]
        n = live.shape[0]
        scores, matched = _eval_node(spec, arrays, seg, n)
        eligible = matched & live
        count = jnp.sum(eligible, dtype=jnp.int32)
        total = jax.lax.psum(count, axis)
        shard_id = jax.lax.axis_index(axis).astype(jnp.int32)
        if k > 0:
            from ..ops.bm25_device import sort_key_plane

            kk = min(k, n)
            iota = jnp.arange(n, dtype=jnp.int32)
            local_after = a_doc - shard_id * docs_per_shard
            if sort_field is not None:
                col, key = sort_key_plane(
                    seg, sort_field, sort_desc, missing_first
                )
                keep = eligible
                if has_after:
                    keep = keep & (
                        (key > a_key)
                        | ((key == a_key) & (iota > local_after))
                    )
                masked = jnp.where(keep, key, jnp.float32(jnp.inf))
                neg, ids = jax.lax.top_k(-masked, kk)
                local_key = -neg  # ascending merge-key space
                local_val = col[ids]  # raw values (NaN = missing)
            else:
                keep = eligible
                if has_after:
                    keep = keep & (
                        (scores < a_key)
                        | ((scores == a_key) & (iota > local_after))
                    )
                masked = jnp.where(keep, scores, jnp.float32(NEG_INF))
                top_s, ids = jax.lax.top_k(masked, kk)
                local_key = -top_s  # score desc == key asc
                local_val = top_s
            n_after = jnp.sum(keep, dtype=jnp.int32)
            gids = shard_id * docs_per_shard + ids.astype(jnp.int32)
            all_key = jax.lax.all_gather(local_key, axis).reshape(-1)
            all_val = jax.lax.all_gather(local_val, axis).reshape(-1)
            all_gid = jax.lax.all_gather(gids, axis).reshape(-1)
            m = min(k, all_key.shape[0])
            # Stable top-k over -key: equal keys favor the lower flat
            # index = (shard, per-shard rank) — the host merge tiebreak.
            neg_top, idxm = jax.lax.top_k(-all_key, m)
            out_key = -neg_top
            out_val = all_val[idxm]
            out_gid = all_gid[idxm]
            n_after_total = jax.lax.psum(n_after, axis)
        else:  # agg-only / count-only request: no hits merge at all
            out_key = jnp.zeros(0, dtype=jnp.float32)
            out_val = jnp.zeros(0, dtype=jnp.float32)
            out_gid = jnp.zeros(0, dtype=jnp.int32)
            n_after_total = jnp.zeros((), dtype=jnp.int32)
        if aggs_spec is not None:
            results = tuple(
                _eval_agg(s, a, seg, eligible, scores, n)
                for s, a in zip(aggs_spec, agg_arrays)
            )
            results = mesh_combine(aggs_spec, results, axis)
            # Leading [1, ...] axis so P(axis) out-specs stack per-shard
            # planes to [S, ...]; psum'd (replicated) leaves stack to
            # identical rows — the host reads row 0 for those.
            agg_out = jax.tree.map(lambda x: x[None], results)
        else:
            agg_out = ()
        return out_key, out_val, out_gid, total, n_after_total, agg_out

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(), P()),
        out_specs=(P(), P(), P(), P(), P(), P(axis)),
    )(
        seg_stacked,
        arrays_stacked,
        aggs_arrays_stacked,
        jnp.float32(after_key),
        jnp.int32(after_doc),
    )


@partial(
    jax.jit,
    static_argnames=("mesh", "shard_axis", "batch_axis", "spec", "k", "docs_per_shard"),
)
def sharded_execute_batch(
    mesh: Mesh,
    shard_axis: str,
    batch_axis: str,
    seg_stacked,
    arrays_batched,  # leaves [Q, S, ...]
    spec,
    k: int,
    docs_per_shard: int,
):
    """Query-batch × shard SPMD search over a 2D mesh.

    The replica/data-parallel analog (SURVEY §2.3 row 2): the index is
    replicated over `batch_axis` and sharded over `shard_axis`; a batch of
    same-shape compiled queries is sharded over `batch_axis`. Each device
    scores its query sub-batch against its shard; the shard reduce is an
    `all_gather` over ICI exactly as in `sharded_execute`.

    Returns (scores f32[Q, k], global ids i32[Q, k], totals i32[Q]), sharded
    over `batch_axis`.
    """

    def body(seg, arrays):
        seg = jax.tree.map(lambda x: x[0], seg)  # strip shard axis
        arrays = jax.tree.map(lambda x: x[:, 0], arrays)  # [Qb, ...]
        live = seg["live"]
        n = live.shape[0]
        kk = min(k, n)

        def one(one_arrays):
            if supports_sparse(spec):
                return _sparse_inner(seg, spec, one_arrays, kk)
            scores, matched = _eval_node(spec, one_arrays, seg, n)
            eligible = matched & live
            masked = jnp.where(eligible, scores, jnp.float32(NEG_INF))
            local_s, local_i = jax.lax.top_k(masked, kk)
            return local_s, local_i, jnp.sum(eligible, dtype=jnp.int32)

        local_s, local_i, counts = jax.vmap(one)(arrays)  # [Qb, kk]
        shard_id = jax.lax.axis_index(shard_axis).astype(jnp.int32)
        global_i = shard_id * docs_per_shard + local_i.astype(jnp.int32)
        all_s = jax.lax.all_gather(local_s, shard_axis)  # [S, Qb, kk]
        all_i = jax.lax.all_gather(global_i, shard_axis)
        qb = all_s.shape[1]
        flat_s = all_s.transpose(1, 0, 2).reshape(qb, -1)  # [Qb, S*kk]
        flat_i = all_i.transpose(1, 0, 2).reshape(qb, -1)
        top_s, idx = jax.lax.top_k(flat_s, min(k, flat_s.shape[-1]))
        top_i = jnp.take_along_axis(flat_i, idx, axis=1)
        totals = jax.lax.psum(counts, shard_axis)
        return top_s, top_i, totals

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(shard_axis), P(batch_axis, shard_axis)),
        out_specs=(P(batch_axis), P(batch_axis), P(batch_axis)),
    )(seg_stacked, arrays_batched)
