"""SPMD mesh serving: REST `_search` through one shard_map program.

This wires `parallel/sharded.py` into the PRODUCTION search path. A
multi-shard index whose shard count fits the device mesh serves its query
phase as a single SPMD program — per-shard scoring + local top-k, then the
coordinator reduce as `all_gather`/`psum` collectives over ICI — instead of
the host-side per-shard loop in `ShardedSearchCoordinator._scatter_merge`.
The reference's analogous path is the transport-level scatter/gather
(action/search/AbstractSearchAsyncAction.java:280) plus coordinator reduce
(action/search/SearchPhaseController.java:398); here both collapse into
collectives (SURVEY §2.3 rows 1/3).

Design:

- `MeshView` maintains a device-resident "searchable snapshot" of the
  index: one merged segment per shard (its engine's live docs, in
  host-path global-doc order), packed onto that shard's mesh device.
- **Delta-scaled refresh** (ROADMAP item 4): per-shard buffers are keyed
  by the engine's monotonic refresh `generation`; a search only re-merges
  shards whose generation moved. Within a changed shard, the merge is
  TOKENIZATION-FREE posting concatenation (index/merge.py): per-handle
  live-compacted pieces are cached by (handle uid, live epoch) — the
  PR-9 cache-key scheme — so only NEW or merged handles compact, and the
  concatenation itself is pure array ops (zero analysis calls,
  hook-counted via estpu_analysis_calls_total). On the device side,
  `pack_segment_delta` compares the merged host arrays against the
  previous snapshot's and re-uploads only the planes the delta actually
  touched (an append-only one-doc refresh re-uploads the written fields'
  postings + the live mask; untouched fields' tile planes are shared
  with the previous snapshot) — counted as
  estpu_mesh_field_planes_{packed,reused}_total. The global stacked
  arrays are re-assembled zero-copy from the per-shard device buffers
  with `jax.make_array_from_single_device_arrays`. Padded doc/tile
  shapes grow in pow-2 steps, so unchanged shards' buffers stay valid
  across growth-free refreshes; any shape growth or schema change
  rebuilds every shard (geometric, so amortized-incremental). KNOWN
  COST: a changed shard's merged postings still re-CONCATENATE in full
  (array I/O, not analysis) because the stacked planes interleave
  handles term-major; per-handle device subplanes would need multi-span
  term worklists in the compiler.
- **Filter-cache rows survive refresh**: mesh-path mask planes are
  cached per SHARD ROW, keyed by the shard's (handle uid, live epoch)
  signature instead of the old generation sum (which killed every
  stacked plane on any refresh). A one-shard refresh rebuilds only that
  shard's row (one single-shard mask launch); unchanged shards' rows
  keep hitting, and the [S, N] stacked plane is re-assembled zero-copy
  from the cached rows (see MeshIndex._apply_filter_cache).
- **Statistics parity**: plans compile with statistics aggregated from the
  ENGINE segments (tombstones included — Lucene keeps deleted docs in
  term stats until merge), exactly what `ShardedSearchCoordinator.
  global_stats` feeds the host path, so mesh scores are bit-identical to
  host-loop scores. Because those stats drift between shard uploads, the
  packed precomputed-impact (tn) planes may go stale; `MeshIndex.
  _tn_avgdl` reports each field's PACK-TIME scope so the query compiler
  falls back to the norm-cache gather kernel (`terms_gather`) whenever
  they don't match — the same staleness contract the engine's own
  `_sync_impacts` path uses.
- The fetch phase (source/highlight/docvalue_fields/fields) stays on the
  host against the snapshot's merged segments, mirroring the reference's
  query-then-fetch split.

One launch serves the full production request shape: sorted searches
(single numeric doc-values key, asc/desc, missing first/last, optional
trailing `_doc` tiebreak — ranked by an encoded (sort key, shard, doc)
composite and merged by in-program collectives), `search_after` cursors
(a key-range mask applied before the local top-k), aggregations in the
mesh-eligible family (metric/percentile family, fixed-edge histogram/
range with psum'd integer count planes, keyword/numeric terms,
cardinality, and the filter/global/missing nesting family), and `size:0`
agg-only / count-only requests. Requests outside the supported shape
(rescore, profile, multi-key field sorts, array-bucket aggs with metric
subs, top_hits/composite/matrix_stats/significant_terms) fall back to
the host-loop coordinator — counted by reason in
`estpu_mesh_fallback_total`, never silently. Result parity between the
two paths is asserted bit-exactly by tests/test_mesh_serving.py and the
tests/test_mesh_sorted_aggs.py fuzz suite.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

from ..index.filter_cache import mesh_cache_scope
from ..index.merge import compact_segment, concat_segments
from ..index.segment import Segment
from ..index.tiles import TILE, device_nbytes, pack_segment_delta
from ..obs.metrics import timed_launch
from ..ops.bm25_device import segment_tree
from ..query.compile import FieldStats, aggregate_field_stats
from .sharded import (
    ShardedIndex,
    fill_union_schema,
    sharded_execute,
    sharded_execute_request,
    union_schema,
)


def _pow2(n: int, floor: int = 1) -> int:
    return 1 << max(0, max(n, floor) - 1).bit_length()


# Error classification for the serving breaker. Sticky failures are wrong-
# answer or will-never-work conditions (plan/compile bugs, parity breaks):
# retrying them risks serving bad results or paying a doomed compile per
# request forever. Transient failures are capacity/runtime conditions
# (device OOM holding the mesh copy, executor hiccups) that clear when
# pressure does.
_STICKY_ERROR_TYPES = (TypeError, ValueError, NotImplementedError, AssertionError)
_STICKY_ERROR_TOKENS = ("INVALID_ARGUMENT", "parity", "mismatch")
_TRANSIENT_ERROR_TOKENS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
    "OOM",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
)


def classify_mesh_error(e: BaseException) -> str:
    """'sticky' | 'transient' for an execute-stage mesh failure."""
    text = str(e)
    if isinstance(e, MemoryError) or any(
        tok in text for tok in _TRANSIENT_ERROR_TOKENS
    ):
        return "transient"
    if isinstance(e, _STICKY_ERROR_TYPES) or any(
        tok.lower() in text.lower() for tok in _STICKY_ERROR_TOKENS
    ):
        return "sticky"
    # Unknown runtime failures are treated as transient: a cooldown'd
    # retry is recoverable, a permanent disable is not.
    return "transient"


class MeshServingBreaker:
    """Circuit breaker for the SPMD serving path.

    closed → (threshold transient failures) → open → [cooldown] →
    half-open → closed on the first success / back to open on failure.
    Sticky failures (see classify_mesh_error) latch the breaker open for
    the life of the process — those need a code fix, not a retry. Disable
    and re-enable transitions are counted for `_nodes/stats`.
    """

    def __init__(
        self,
        failure_threshold: int | None = None,
        cooldown_s: float | None = None,
    ):
        if failure_threshold is None:
            failure_threshold = int(
                os.environ.get("ESTPU_MESH_BREAKER_FAILURES", 3)
            )
        if cooldown_s is None:
            cooldown_s = float(
                os.environ.get("ESTPU_MESH_BREAKER_COOLDOWN_S", 30.0)
            )
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self.state = "closed"  # closed | open | half_open
        self.sticky = False
        self.failures = 0  # consecutive transient failures while closed
        self.opened_at = 0.0
        self.disable_events = 0
        self.reenable_events = 0
        self.last_error = ""
        self._lock = threading.Lock()

    def allow(self) -> bool:
        """May the next request try the mesh? Flips open → half-open once
        the cooldown has elapsed (that request is the trial)."""
        with self._lock:
            if self.sticky:
                return False
            if self.state == "open":
                if time.monotonic() - self.opened_at >= self.cooldown_s:
                    self.state = "half_open"
                    return True
                return False
            return True

    def is_open(self) -> bool:
        """Side-effect-free probe: is the mesh path currently not served?
        (Unlike allow(), never performs the open → half-open transition.)"""
        with self._lock:
            if self.sticky:
                return True
            return (
                self.state == "open"
                and time.monotonic() - self.opened_at < self.cooldown_s
            )

    def record_failure(self, e: BaseException) -> None:
        with self._lock:
            self.last_error = f"{type(e).__name__}: {e}"
            if classify_mesh_error(e) == "sticky":
                self.sticky = True
                if self.state != "open":
                    self.disable_events += 1
                self.state = "open"
                self.opened_at = time.monotonic()
                return
            self.failures += 1
            if self.state == "half_open" or self.failures >= self.failure_threshold:
                if self.state != "open":
                    self.disable_events += 1
                self.state = "open"
                self.opened_at = time.monotonic()
                self.failures = 0

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            if self.state == "half_open":
                self.state = "closed"
                self.reenable_events += 1

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "state": "disabled" if self.sticky else self.state,
                "sticky": self.sticky,
                "failure_threshold": self.failure_threshold,
                "cooldown_seconds": self.cooldown_s,
                "disable_events": self.disable_events,
                "reenable_events": self.reenable_events,
                "last_error": self.last_error,
            }


@dataclass
class _MeshHandle:
    """Host-side handle for a snapshot's merged shard segment (duck-typed
    for SearchService._fetch_source/_fetch_highlight/_fetch_fields, which
    only read handle.segment; the agg compile additionally reads
    handle.device, and the mesh agg merge reads handle.spans)."""

    segment: Segment
    # The packed DeviceSegment behind the stacked pytree row (same device
    # buffers, host-side field/column views for the agg planner).
    device: Any = None
    # Engine-handle boundaries inside the merged doc space: [lo, hi) per
    # original segment, in handle order. The f64-exact metric folds walk
    # these spans so their partial sums group exactly like the host loop's
    # per-segment folds (bit-identical results).
    spans: list = dc_field(default_factory=list)


@dataclass
class MeshIndex(ShardedIndex):
    """A ShardedIndex whose statistics scope is injected (engine-derived)
    and whose tn validity tracks per-shard pack time."""

    serving_stats: dict[str, FieldStats] | None = None
    pack_avgdls: list[dict[str, float]] | None = None
    # Per-shard content signatures — tuple of (handle uid, live epoch)
    # per shard — and per-shard (non-stacked) device seg trees: the
    # row-granular filter-cache machinery below keys mask-plane rows on
    # the former and rebuilds a single missing row on the latter.
    shard_sigs: tuple = ()
    shard_trees: list = dc_field(default_factory=list)

    def field_stats(self) -> dict[str, FieldStats]:
        if self.serving_stats is not None:
            return self.serving_stats
        return super().field_stats()

    def _apply_filter_cache(
        self, query, compiled, record: bool = True, entries: list | None = None
    ):
        """Row-granular mesh filter cache: mask planes are cached per
        SHARD ROW, keyed on the shard's (handle uid, live epoch)
        signature — the same uid scheme the solo filter/ANN caches use —
        so a refresh of one shard invalidates ONLY that shard's row.
        The [S, N] stacked plane the kernel consumes is re-assembled
        zero-copy from the cached rows (each row already lives on its
        shard's mesh device); a missing row is rebuilt with a
        single-shard `compute_filter_mask` launch against that shard's
        own seg tree. Bit-exactness holds because the stacked builder
        was itself a vmap of the same per-shard mask program
        (ops/bm25_device.compute_filter_mask_stacked), gated by the
        tests/test_mesh_refresh.py fuzz. The assembled [S, N] view is
        deliberately NOT cached: it shares the rows' device buffers
        zero-copy, so a cached view would pin HBM past the rows' own
        eviction — re-assembly is a metadata-only operation paid per
        request (S row gets + one make_array call)."""
        cache = self.filter_cache
        if cache is None or not self.shard_sigs:
            return super()._apply_filter_cache(query, compiled, record, entries)
        from ..index.filter_cache import (
            apply_cached_masks,
            record_filter_usage,
        )
        from ..ops.bm25_device import compute_filter_mask

        if entries is None:
            entries = record_filter_usage(cache, query, record=record)
        if not entries:
            return compiled, {}
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self.mesh, P(self.axis))
        n_shards = self.n_shards
        npad = self.docs_per_shard
        scope = self.cache_scope

        def build(child_spec, child_arrays, norm):
            rows = []
            hit_rows = 0
            for s in range(n_shards):
                rkey = (
                    scope,
                    ("row", s, self.shard_sigs[s], npad),
                    0,
                    norm,
                )
                row = cache.get(rkey)
                if row is None:
                    arrays_s = jax.tree.map(
                        lambda x: x[s], child_arrays
                    )
                    row = compute_filter_mask(
                        self.shard_trees[s], child_spec, arrays_s
                    ).reshape(1, -1)
                    cache.put(rkey, row, int(row.nbytes))
                else:
                    hit_rows += 1
                rows.append(row)
            if hit_rows:
                cache.note_reuse(hit_rows)
            shape = (n_shards, npad)
            index_map = sharding.addressable_devices_indices_map(shape)
            ordered = [
                rows[idx[0].start if idx[0].start is not None else 0]
                for _, idx in index_map.items()
            ]
            plane = jax.make_array_from_single_device_arrays(
                shape, sharding, ordered
            )
            return plane, 0

        compiled, masks, _reused = apply_cached_masks(
            cache, (scope, 0, 0), query, compiled, build,
            const_fill=lambda: {
                "boost": np.zeros(self.n_shards, dtype=np.float32)
            },
            entries=entries,
            store_planes=False,
        )
        return compiled, masks

    def _tn_avgdl(self, shard: int, field: str, fstats) -> float:
        # The compiled spec KIND must stay shard-uniform (one shard_map
        # program): only report a valid tn scope when every shard packed
        # the field with the same avgdl; any divergence forces the gather
        # kernel everywhere.
        if not self.pack_avgdls:
            return -1.0
        vals = {d.get(field) for d in self.pack_avgdls}
        if len(vals) == 1:
            v = vals.pop()
            if v is not None:
                return float(v)
        return -1.0


@dataclass
class _Snapshot:
    """One immutable generation-consistent serving view."""

    gens: tuple
    index: MeshIndex
    handles: list[_MeshHandle]
    # The pinned engine segment handles the serving statistics came from
    # (flat, shard order): the agg planner's histogram-range scope, so
    # plan-time behavior (bucket windows, TooManyBuckets) matches the
    # host-loop coordinator exactly — tombstoned values included.
    engine_handles: list = dc_field(default_factory=list)


class MeshView:
    """Generation-consistent device mesh view of one index's shards."""

    def __init__(self, engines, mappings, params, mesh, axis: str = "shard",
                 filter_cache=None):
        self.engines = engines
        self.mappings = mappings
        self.params = params
        self.mesh = mesh
        self.axis = axis
        # index.filter_cache.FilterCache (the node's, when wired by
        # create_index): the plain-scoring serve path substitutes cached
        # [S, N] mask planes for repeated filter clauses. Planes are
        # cached per SHARD ROW keyed on (handle uid, live epoch)
        # signatures (MeshIndex._apply_filter_cache), so a refresh of one
        # shard invalidates only that shard's row; rows of unchanged
        # shards keep hitting. Stale rows/views are purged eagerly on
        # snapshot change (purge_scope).
        self.filter_cache = filter_cache
        self._lock = threading.Lock()
        self._snap: _Snapshot | None = None
        # Per-shard cache reused across refreshes.
        n = len(engines)
        self._host_segs: list[Segment | None] = [None] * n
        # Per-handle live-compacted pieces, keyed (handle uid, live
        # epoch): a refresh re-compacts only handles whose key is new
        # (fresh segment, merge output, or a live-mask sync); unchanged
        # handles reuse their piece — the host-side half of delta
        # scaling. Pruned to the engines' live handle set every refresh.
        self._pieces: dict[tuple[int, int], Segment] = {}
        # Per-shard content signature: tuple of (uid, live_epoch) in
        # handle order — the filter-cache row key component and the
        # skip-repack check (a generation bump that leaves a shard's
        # signature unchanged needs no re-merge).
        self._shard_sig: list[tuple | None] = [None] * n
        # Union-schema-filled copies actually packed (what snapshots see).
        self._filled_segs: list[Segment | None] = [None] * n
        self._trees: list[Any] = [None] * n  # [1, ...]-leaved device pytrees
        self._devs: list[Any] = [None] * n  # packed DeviceSegments (views)
        self._spans: list[list] = [[] for _ in range(n)]  # handle spans
        self._pack_avgdl: list[dict[str, float]] = [{} for _ in range(n)]
        self._shapes: dict[str, Any] | None = None  # current padded shapes
        # Test/observability hooks.
        self.served = 0  # searches answered by the SPMD program
        self.packs = 0  # shard pack+upload operations performed
        self.seg_reuses = 0  # shard buffers reused across refreshes
        self.rebuilds = 0  # full (all-shard) rebuilds
        # Fallback accounting: every serve() decline is counted by reason
        # (never silent) — mirrored on the metrics registry as
        # estpu_mesh_fallback_total{reason} and surfaced in `_nodes/stats`
        # under mesh_serving; the coordinator tags the request span with
        # last_fallback_reason.
        self.fallbacks: dict[str, int] = {}
        self.last_fallback_reason: str | None = None
        # obs.MetricsRegistry (the node's, when wired by create_index);
        # standalone views get a private registry.
        from ..obs.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        # Resilience: execute-stage failures route requests back to the
        # host-loop path through a circuit breaker — transient failures
        # (device OOM under the mesh copy) half-open after a cooldown and
        # re-enable on the first success; sticky failures (compile/parity
        # bugs) stay off for the life of the process.
        self.exec_failures = 0  # lifetime count, for _nodes/stats
        self.breaker = MeshServingBreaker()
        # exec.ExecPlanner (set by the node): SPMD servings are recorded
        # as "mesh_spmd" decisions with their observed latency, so the
        # node-wide cost model and `_nodes/stats` counters see this
        # backend's traffic alongside device/blockmax/oracle.
        self.planner = None
        # obs.DeviceInstruments + obs.device.HbmLedger (set by the node):
        # per-launch timing for the one-launch SPMD program, and ledger
        # registration of the mesh snapshot's device bytes under label
        # "mesh_plane" scoped to this index's mesh cache scope.
        self.device = None
        self.ledger = None
        self.plane_bytes = 0  # current snapshot's registered device bytes

    @property
    def disabled(self) -> bool:
        """Back-compat view of the breaker: True while the SPMD path is
        not being tried (sticky-latched or cooling down)."""
        return self.breaker.is_open()

    # ------------------------------------------------------------- refresh

    def _merged_segment(self, handles: list) -> tuple[Segment, list]:
        """One segment of the shard's device-visible live docs, in host-path
        order (segment handles in order, local ids ascending) so equal-score
        tie-breaks match the coordinator merge exactly. Also returns the
        [lo, hi) span each engine handle occupies in the merged doc space
        (the f64-exact agg folds group by these).

        Tokenization-free: each handle contributes a live-compacted PIECE
        (index/merge.compact_segment — a flatnonzero gather, cached by
        (uid, live epoch) so only new/changed handles compact) and the
        pieces concatenate as pure array ops (concat_segments). No
        document is re-analyzed — the SegmentBuilder re-add loop this
        replaces re-tokenized the whole shard on every one-doc refresh."""
        pieces: list[Segment] = []
        spans: list[tuple[int, int]] = []
        base = 0
        for handle in handles:
            key = (handle.uid, handle.live_epoch)
            piece = self._pieces.get(key)
            if piece is None:
                # The mask the device kernels currently serve — NOT
                # live_host, which may carry deletes that only become
                # searchable at the next refresh (generation bump) on the
                # host path too.
                live = np.asarray(handle.device.live)[
                    : handle.segment.num_docs
                ]
                piece = compact_segment(handle.segment, live)
                self._pieces[key] = piece
            pieces.append(piece)
            spans.append((base, base + piece.num_docs))
            base += piece.num_docs
        return concat_segments(pieces), spans

    def _schema(self, segs: list[Segment]) -> dict[str, Any]:
        """Union schema + pow-2 padded shapes covering every shard."""
        fields, dv, vec = union_schema(segs)
        docs = 1
        tiles: dict[str, int] = {}
        pos_tiles: dict[str, int] = {}
        for seg in segs:
            docs = max(docs, seg.num_docs)
        for seg in segs:
            for name, has_norms in fields.items():
                f = seg.fields.get(name)
                postings = len(f.doc_ids) if f is not None else 0
                tiles[name] = max(
                    tiles.get(name, 0), _pow2(postings // TILE + 2)
                )
                if has_norms:
                    npos = (
                        len(f.positions)
                        if f is not None and f.positions is not None
                        else 0
                    )
                    pos_tiles[name] = max(
                        pos_tiles.get(name, 0), _pow2(npos // TILE + 2)
                    )
        return {
            "fields": fields,
            "dv": dv,
            "vec": vec,
            "docs": _pow2(docs),
            "tiles": tiles,
            "pos_tiles": pos_tiles,
        }

    @staticmethod
    def _shapes_fit(old: dict[str, Any] | None, new: dict[str, Any]) -> bool:
        """True when buffers packed under `old` remain stackable with
        shards packed under shapes covering `new` (schema identical, no
        padded dimension grew)."""
        if old is None:
            return False
        if (
            old["fields"] != new["fields"]
            or old["dv"] != new["dv"]
            or old["vec"] != new["vec"]
        ):
            return False
        if new["docs"] > old["docs"]:
            return False
        for name, t in new["tiles"].items():
            if t > old["tiles"].get(name, 0):
                return False
        for name, t in new["pos_tiles"].items():
            if t > old["pos_tiles"].get(name, 0):
                return False
        return True

    def _pack_shard(self, shard: int, seg: Segment, shapes: dict[str, Any],
                    stats: dict[str, FieldStats],
                    delta_ok: bool = False):
        """Pack one shard's merged segment onto its mesh device; leaves get
        a leading [1, ...] axis for the global-array assembly. Returns
        (tree, filled segment, pack avgdls) — the caller commits them into
        the per-shard caches only once EVERY shard packed, so a mid-rebuild
        failure can't leave mixed-shape buffers behind.

        `delta_ok` (padded shapes unchanged) enables plane-level upload
        skipping: pack_segment_delta compares the merged host arrays
        against the previous snapshot's filled segment and reuses every
        device plane the delta didn't touch — the device half of the
        delta-scaled refresh, counted as
        estpu_mesh_field_planes_{packed,reused}_total.

        The union-schema fill COPIES the segment (fill_union_schema):
        `seg` stays pristine in the per-shard cache, and segments held by a
        previous, still-serving snapshot are never mutated under a
        concurrent compile."""
        import jax

        device = self.mesh.devices.reshape(-1)[shard]
        seg = fill_union_schema(
            seg, shapes["fields"], shapes["dv"], shapes["vec"]
        )
        avgdl = {
            name: (stats[name].avgdl if name in stats else 1.0)
            for name in shapes["fields"]
        }
        prev_seg = self._filled_segs[shard] if delta_ok else None
        prev_dev = self._devs[shard] if delta_ok else None
        dev, reused, packed = pack_segment_delta(
            seg,
            prev_seg,
            prev_dev,
            device=device,
            pad_docs_to=shapes["docs"],
            field_min_tiles=shapes["tiles"],
            field_avgdl=avgdl,
            k1=self.params.k1,
            b=self.params.b,
            field_pos_min_tiles=shapes["pos_tiles"],
        )
        if reused or packed:
            self.metrics.counter(
                "estpu_mesh_field_planes_reused_total",
                "Mesh refresh device planes shared with the previous "
                "snapshot (upload skipped: host arrays byte-identical)",
            ).inc(reused)
            self.metrics.counter(
                "estpu_mesh_field_planes_packed_total",
                "Mesh refresh device planes re-packed and re-uploaded",
            ).inc(packed)
        # agg_segment_tree = segment_tree + keyword ordinal planes: the
        # one stacked pytree serves both the scoring kernels and the
        # in-program aggregation planes.
        from ..ops.aggs_device import agg_segment_tree

        tree = jax.tree.map(lambda x: x[None], agg_segment_tree(dev))
        return tree, seg, avgdl, dev

    def _assemble(self) -> Any:
        """Zero-copy global stacked pytree from the per-shard buffers."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        flats = []
        treedef = None
        for tree in self._trees:
            leaves, treedef = jax.tree.flatten(tree)
            flats.append(leaves)
        sharding = NamedSharding(self.mesh, P(self.axis))
        n = len(flats)
        out_leaves = []
        for li in range(len(flats[0])):
            per_shard = [flats[s][li] for s in range(n)]
            shape = (n,) + tuple(per_shard[0].shape[1:])
            index_map = sharding.addressable_devices_indices_map(shape)
            arrays = [
                per_shard[idx[0].start if idx[0].start is not None else 0]
                for _, idx in index_map.items()
            ]
            out_leaves.append(
                jax.make_array_from_single_device_arrays(
                    shape, sharding, arrays
                )
            )
        return jax.tree.unflatten(treedef, out_leaves)

    def _pin_engines(self) -> tuple[tuple, list[list]]:
        """(generations, per-engine segment-handle lists) read atomically
        per engine under its lock, so generation and handle list can never
        disagree — merged device data, recorded generations, and serving
        statistics all derive from this one pinned view."""
        gens = []
        pinned = []
        for e in self.engines:
            with e.lock:
                gens.append(e.generation)
                pinned.append(list(e.segments))
        return tuple(gens), pinned

    def _ensure(self) -> _Snapshot:
        """Refresh the mesh view to the engines' current generations."""
        snap = self._snap
        if snap is not None and snap.gens == tuple(
            e.generation for e in self.engines
        ):
            return snap
        with self._lock:
            gens, pinned = self._pin_engines()
            snap = self._snap
            if snap is not None and snap.gens == gens:
                return snap
            import jax

            n = len(self.engines)
            # Content signatures: a generation bump whose shard signature
            # is unchanged (e.g. another shard's write) needs no re-merge.
            sigs = [
                tuple((h.uid, h.live_epoch) for h in pinned[i])
                for i in range(n)
            ]
            changed = [
                i for i in range(n)
                if self._shard_sig[i] != sigs[i]
                or self._host_segs[i] is None
            ]
            merged = {
                i: s for i, s in enumerate(self._host_segs) if s is not None
            }
            spans = {i: self._spans[i] for i in merged}
            for i in changed:
                merged[i], spans[i] = self._merged_segment(pinned[i])
            # Prune compaction pieces of handles no longer serving
            # (merged away, dropped): keyed (uid, live_epoch) like the
            # filter/ANN cache entries they mirror.
            live_keys = {
                (h.uid, h.live_epoch)
                for handles in pinned
                for h in handles
            }
            self._pieces = {
                k: v for k, v in self._pieces.items() if k in live_keys
            }
            new_shapes = self._schema([merged[i] for i in sorted(merged)])
            # Serving statistics: the ENGINE view (tombstones included),
            # computed from the same pinned handle lists the merges came
            # from — identical to ShardedSearchCoordinator.global_stats at
            # these generations, and device data and statistics can never
            # mix generations.
            stats = aggregate_field_stats(
                [h.segment for handles in pinned for h in handles]
            )
            if self._shapes_fit(self._shapes, new_shapes):
                shapes = self._shapes
                to_pack = changed
                delta_ok = True
            else:
                shapes = new_shapes
                to_pack = list(range(n))
                delta_ok = False
            # Stage every pack, then commit atomically: a failure here
            # leaves all per-shard caches untouched (old snapshot keeps
            # serving; the gen mismatch retries the refresh next search).
            packed = {
                i: self._pack_shard(i, merged[i], shapes, stats,
                                    delta_ok=delta_ok)
                for i in to_pack
            }
            if shapes is not self._shapes:
                self._shapes = shapes
                self.rebuilds += 1
            for i in changed:
                self._host_segs[i] = merged[i]
                self._spans[i] = spans[i]
            for i, (tree, filled, avgdl, dev) in packed.items():
                self._trees[i] = tree
                self._filled_segs[i] = filled
                self._pack_avgdl[i] = avgdl
                self._devs[i] = dev
                self.packs += 1
            self.seg_reuses += n - len(to_pack)
            self.metrics.counter(
                "estpu_mesh_segments_packed_total",
                "Mesh refresh shard segments re-merged and re-packed",
            ).inc(len(to_pack))
            self.metrics.counter(
                "estpu_mesh_segments_reused_total",
                "Mesh refresh shard segments served from unchanged "
                "buffers (no re-merge, no re-upload)",
            ).inc(n - len(to_pack))
            self._shard_sig = list(sigs)
            scope = mesh_cache_scope(self.engines)
            docs_pad = self._shapes["docs"]
            if self.filter_cache is not None:
                # Eager purge of mask rows no refresh can serve again —
                # dead signatures free their HBM now instead of waiting
                # for LRU. Live rows (unchanged shards) survive: that is
                # the delta-scaled cache-survival contract.
                keep = {
                    ("row", s, sigs[s], docs_pad) for s in range(n)
                }
                self.filter_cache.purge_scope(scope, keep)
            # HBM ledger: this snapshot's resident device bytes (shared
            # delta-reused planes count once — device_nbytes walks the
            # CURRENT views). The registration swaps atomically with the
            # snapshot commit; the consistency-law twin is plane_bytes.
            nbytes = sum(
                device_nbytes(d) for d in self._devs if d is not None
            )
            if self.ledger is not None:
                # Register BEFORE releasing the previous snapshot's
                # bytes: both snapshots coexist across the swap (delta
                # reuse aside), and the high watermark must see it.
                self.ledger.register("mesh_plane", scope, nbytes)
                self.ledger.release("mesh_plane", scope, self.plane_bytes)
            self.plane_bytes = nbytes
            segments = [s for s in self._filled_segs]
            index = MeshIndex(
                mesh=self.mesh,
                axis=self.axis,
                mappings=self.mappings,
                segments=segments,
                seg_stacked=self._assemble(),
                docs_per_shard=docs_pad,
                params=self.params,
                serving_stats=stats,
                pack_avgdls=list(self._pack_avgdl),
                filter_cache=self.filter_cache,
                cache_scope=scope,
                cache_generation=sum(gens),
                shard_sigs=tuple(sigs),
                shard_trees=[
                    jax.tree.map(lambda x: x[0], t) for t in self._trees
                ],
            )
            self._snap = _Snapshot(
                gens=gens,
                index=index,
                handles=[
                    _MeshHandle(s, device=self._devs[i], spans=self._spans[i])
                    for i, s in enumerate(segments)
                ],
                engine_handles=[h for handles in pinned for h in handles],
            )
            return self._snap

    def release_ledger(self) -> None:
        """Release this view's mesh-plane ledger registration (index
        deletion: the snapshot's device arrays die with the view)."""
        if self.ledger is not None and self.plane_bytes:
            self.ledger.release(
                "mesh_plane", mesh_cache_scope(self.engines),
                self.plane_bytes,
            )
        self.plane_bytes = 0

    # -------------------------------------------------------------- serve

    def _fallback(self, reason: str):
        """Count (never silently drop) one serve() decline and return the
        None the coordinator interprets as host-loop fallback. The reason
        is attached to the ENCLOSING mesh.serve span as an event from this
        thread's own trace context (race-free under concurrent searches);
        last_fallback_reason is a single-threaded test/diagnostic hook."""
        from ..obs.tracing import TRACER

        self.last_fallback_reason = reason
        with self._lock:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1
        self.metrics.counter(
            "estpu_mesh_fallback_total",
            "SPMD mesh fallbacks to the host-loop coordinator by reason",
            reason=reason,
        ).inc()
        TRACER.event("mesh.fallback", reason=reason)
        return None

    @staticmethod
    def ineligible_reason(request) -> str | None:
        """Shape-level reason this request cannot serve on the SPMD path
        (None = eligible). Context-free — mapping/plan-level declines
        (unsortable field, non-uniform compile) surface inside serve()."""
        from ..search.aggs import mesh_agg_ineligible_reason
        from ..search.service import normalized_sort

        if request.rescore or request.profile:
            return "ineligible_shape"
        if getattr(request, "knn", None) is not None:
            # kNN serves through the host loop's ANN/exact kernels; the
            # stacked-shard SPMD program has no vector planes yet.
            return "knn"
        if request.after_doc >= 0:
            # Engine-global doc cursors (scroll internals) address the
            # host path's doc space, not the mesh's.
            return "ineligible_shape"
        if request.sort is not None:
            keys = normalized_sort(request)
            if len(keys) != 1:
                # Multi-key field sorts lexsort on the host path.
                return "sort_shape"
            fname, desc, _mf = keys[0]
            if fname == "_score" and not desc:
                return "sort_shape"  # bottom-k: host execute_score_asc
        if request.aggs is not None:
            reason = mesh_agg_ineligible_reason(request.aggs)
            if reason is not None:
                return reason
        return None

    @classmethod
    def eligible(cls, request) -> bool:
        """Request shapes the SPMD query phase covers; everything else
        falls back to the host-loop coordinator. Sorted searches
        (single numeric key, asc/desc, missing first/last, optional _doc
        tiebreak), aggregations in the mesh-eligible family, search_after
        cursors and size:0 agg-only/count requests are all served."""
        return cls.ineligible_reason(request) is None

    def _sort_plan(self, request):
        """(sort_field, desc, missing_first, want_sort_values) for the
        kernel, or an ineligibility reason string. sort_field None =
        score-ordered."""
        from ..search.service import normalized_sort

        if request.sort is None:
            return (None, False, False, False)
        ((fname, desc, mfirst),) = normalized_sort(request)
        if fname == "_score":
            return (None, False, False, True)
        fm = self.mappings.get(fname)
        if fm is None or not fm.is_numeric:
            return "sort_shape"  # host path raises the 400 verbatim
        return (fname, desc, mfirst, True)

    def _compile_aggs(self, coordinator, snap, request):
        """(Aggregator, specs tuple, stacked arrays) for the request's agg
        tree, compiled shard-uniform across the mesh. Raises ValueError
        when per-shard lowering diverges (non-uniform filter plans)."""
        from ..search.aggs import Aggregator, _pow2 as agg_pow2

        idx = snap.index
        term_fields: set[str] = set()

        def collect(nodes):
            for n in nodes:
                if n.kind in ("terms", "rare_terms", "cardinality"):
                    f = n.params.get("field")
                    if f:
                        term_fields.add(f)
                collect(n.subs)

        collect(request.aggs)
        term_pads: dict[str, int] = {}
        for f in term_fields:
            widths = [
                h.device.fields[f].num_terms
                for h in snap.handles
                if h.device is not None and f in h.device.fields
            ]
            if widths:
                term_pads[f] = agg_pow2(max(widths))
        agg = Aggregator(
            self.engines[0],
            request.aggs,
            handles=snap.handles,
            index_name=coordinator.index_name,
            term_pads=term_pads,
            range_handles=snap.engine_handles,
        )
        # Keep EVERY shard row (the stacked program is mesh-wide); the
        # default constructor filter drops empty merged shards.
        agg.handles = list(snap.handles)
        import jax

        per_shard = [
            agg.compile_for(snap.handles[s], idx.shard_compiler(s))
            for s in range(len(snap.handles))
        ]
        specs = {s for s, _ in per_shard}
        if len(specs) != 1:
            raise ValueError(
                "aggregation plans did not lower shard-uniform"
            )
        arrays = jax.tree.map(
            lambda *xs: np.stack(xs), *[a for _, a in per_shard]
        )
        return agg, per_shard[0][0], arrays

    def serve(self, coordinator, request, task=None, fc_entries=None):
        """Answer a SearchRequest via ONE SPMD program — scoring, sorted or
        score-ordered top-k with search_after masking, psum'd totals, and
        the aggregation planes all inside a single shard_map launch — or
        None (with the fallback counted by reason) to make the coordinator
        fall back to the host-loop path."""
        from ..search.aggs import new_merge_state
        from ..search.service import SearchHit, SearchResponse, clamp_total

        reason = self.ineligible_reason(request)
        if reason is not None:
            return self._fallback(reason)
        if not self.breaker.allow():
            return self._fallback("breaker")
        if any(
            h.segment.nested for e in self.engines for h in e.segments
        ):
            # Nested blocks are not mesh-stackable yet; without this guard
            # the mesh compiler (which has no nested context) would lower
            # nested queries to match_none and serve wrong results.
            return self._fallback("nested")
        sort_plan = self._sort_plan(request)
        if isinstance(sort_plan, str):
            return self._fallback(sort_plan)
        sort_field, sort_desc, missing_first, want_sort_values = sort_plan
        start = time.monotonic()
        snap = self._ensure()
        idx = snap.index
        try:
            compiled = idx.compile(request.query)
        # staticcheck: ignore[broad-except] compile fallback: non-shard-uniform plans route to the host loop, which re-raises user-facing validation errors identically
        except Exception:
            # Plans the mesh can't make shard-uniform fall back; user-facing
            # validation errors re-raise identically from the host path.
            return self._fallback("non_uniform_plan")
        agg = None
        aggs_spec = None
        aggs_arrays = ()
        if request.aggs is not None:
            try:
                agg, aggs_spec, aggs_arrays = self._compile_aggs(
                    coordinator, snap, request
                )
            # staticcheck: ignore[broad-except] agg-compile fallback: the host loop re-raises user-facing agg validation errors (text-field terms, bad params) identically
            except Exception:
                return self._fallback("non_uniform_plan")
        k = max(0, request.from_) + max(0, request.size)
        if sort_field is not None and k > 0 and sort_field not in (
            idx.segments[0].doc_values if idx.segments else {}
        ):
            # Mapped numeric field no document carries: the host path's
            # missing-column branch owns that shape.
            return self._fallback("sort_shape")
        # search_after cursor, in the kernel's transformed ascending key
        # space; public cursors are key-only, so the global doc tiebreak
        # is pushed past every shard (ties never qualify).
        has_after = request.search_after is not None
        after_key = np.float32(0.0)
        after_doc = len(self.engines) * idx.docs_per_shard
        if has_after:
            raw = request.search_after[0]
            fmax = np.float32(np.finfo(np.float32).max)
            if sort_field is None:
                if raw is None or not isinstance(raw, (int, float)):
                    return self._fallback("ineligible_shape")
                after_key = np.float32(raw)
            elif raw is None:
                after_key = -fmax if missing_first else fmax
            else:
                after_key = np.float32(raw)
                if sort_desc:
                    after_key = np.float32(-after_key)
        if task is not None:
            task.raise_if_cancelled()
        plain = (
            sort_field is None
            and not has_after
            and aggs_spec is None
            and not want_sort_values
            and k > 0
        )
        try:
            if plain:
                # The hot plain-score path keeps the candidate-centric
                # sparse kernel (no dense planes, no agg planes). Filter
                # cache: repeated filter clauses swap in their cached
                # [S, N] mask planes (bit-identical by construction —
                # gated by tests/test_filter_cache.py's mesh fuzz); the
                # sorted/agg one-launch program still recomputes filters
                # (honest residue, ROADMAP item 3).
                seg = idx.seg_stacked
                if idx.filter_cache is not None:
                    # record=False: the coordinator already counted this
                    # request's sighting; recording here too would
                    # double-count whenever execution fails and the
                    # request falls back to the host loop. Its collected
                    # entries ride along so the AST isn't re-walked.
                    compiled, fc_masks = idx._apply_filter_cache(
                        request.query, compiled, record=False,
                        entries=fc_entries,
                    )
                    if fc_masks:
                        seg = {**idx.seg_stacked, "masks": fc_masks}
                with timed_launch(
                    self.device,
                    "mesh_spmd",
                    (compiled.spec, k, None, False, "plain"),
                    "mesh_spmd",
                ) as tl:
                    scores, gids, total = tl.dispatched(
                        sharded_execute(
                            idx.mesh,
                            idx.axis,
                            seg,
                            compiled.arrays,
                            compiled.spec,
                            k,
                            idx.docs_per_shard,
                        )
                    )
                keys = vals = None
                n_after = total
                agg_out = ()
            else:
                with timed_launch(
                    self.device,
                    "mesh_spmd",
                    (
                        compiled.spec, k, sort_field, sort_desc,
                        missing_first, has_after, aggs_spec,
                    ),
                    "mesh_spmd",
                ) as tl:
                    keys, vals, gids, total, n_after, agg_out = tl.dispatched(
                        sharded_execute_request(
                            idx.mesh,
                            idx.axis,
                            idx.seg_stacked,
                            compiled.arrays,
                            compiled.spec,
                            k,
                            idx.docs_per_shard,
                            sort_field=sort_field,
                            sort_desc=sort_desc,
                            missing_first=missing_first,
                            has_after=has_after,
                            after_key=after_key,
                            after_doc=after_doc,
                            aggs_spec=aggs_spec,
                            aggs_arrays_stacked=aggs_arrays,
                        )
                    )
                scores = vals
            import jax

            scores = np.asarray(scores) if scores is not None else None
            gids = np.asarray(gids)
            agg_np = jax.device_get(agg_out)
            total = int(total)
            n_after = int(n_after)
        # staticcheck: ignore[broad-except] execute failures (incl. injected ones) must feed the mesh circuit breaker and fall back — the breaker's error classification is the tested behavior
        except Exception as e:
            # Execute-stage failure (XLA lowering, device OOM holding the
            # mesh copy): fall back to the host loop and feed the breaker —
            # transient failures re-enable after a cooldown'd success,
            # sticky (compile/parity) failures latch off for good.
            self.exec_failures += 1
            self.breaker.record_failure(e)
            return self._fallback("execute_error")
        self.breaker.record_success()
        self.served += 1
        shape = "plain" if sort_field is None else "sorted"
        if aggs_spec is not None:
            shape = shape + "_aggs" if k > 0 else "aggs_only"
        elif k == 0:
            shape = "count_only"
        self.metrics.counter(
            "estpu_mesh_served_total",
            "Searches served by the one-launch SPMD program, by shape",
            shape=shape,
        ).inc()
        if self.planner is not None:
            self.planner.record(
                ("mesh", compiled.spec, k, sort_field, aggs_spec is not None),
                "mesh_spmd",
                time.monotonic() - start,
            )
        aggregations = None
        if agg is not None:
            from ..search.aggs import merge_mesh_result

            states = [new_merge_state(n) for n in request.aggs]
            for node, state, res in zip(request.aggs, states, agg_np):
                merge_mesh_result(node, state, res, snap.handles)
            aggregations = agg.render_states(states)
        timed_out = bool(task is not None and task.check_deadline())
        limit = n_after if has_after else total
        n = min(k, limit, len(gids))
        max_score = None
        if request.sort is None and scores is not None and n > 0:
            max_score = float(scores[0])
        hits = []
        svc = coordinator.services[0]
        for rank in range(max(0, request.from_), n):
            shard, local = idx.locate(int(gids[rank]))
            handle = snap.handles[shard]
            score = None
            sort_out = None
            if sort_field is not None:
                raw = float(scores[rank])
                sort_out = [None if np.isnan(scores[rank]) else raw]
            else:
                score = float(scores[rank])
                if want_sort_values:
                    sort_out = [score]
            hits.append(
                SearchHit(
                    doc_id=handle.segment.ids[local],
                    score=score,
                    source=svc._fetch_source(handle, local, request),
                    sort=sort_out,
                    global_doc=-1,
                    handle=handle,
                    local=local,
                )
            )
        coordinator._apply_fetch_subphases(request, hits)
        total_out, relation = clamp_total(total, request.track_total_hits)
        return SearchResponse(
            took_ms=int((time.monotonic() - start) * 1000),
            total=total_out,
            total_relation=relation,
            max_score=max_score,
            hits=hits,
            aggregations=aggregations,
            shards=len(self.engines),
            timed_out=timed_out,
        )


def maybe_mesh_view(
    engines, mappings, params, filter_cache=None
) -> MeshView | None:
    """A MeshView when SPMD serving can work here: >1 shard, enough local
    devices for one shard per device, and not disabled via
    ESTPU_MESH_SERVING=0."""
    if len(engines) < 2:
        return None
    if os.environ.get("ESTPU_MESH_SERVING", "1") == "0":
        return None
    try:
        import jax
        from jax.sharding import Mesh

        devices = jax.devices()
    # staticcheck: ignore[broad-except] device-probe guard: no usable mesh means host-loop serving, not an error
    except Exception:
        return None
    if len(devices) < len(engines):
        return None
    mesh = Mesh(
        np.array(devices[: len(engines)]), ("shard",)
    )
    return MeshView(engines, mappings, params, mesh, filter_cache=filter_cache)
