from .routing import murmur3_hash, shard_for_id  # noqa: F401
from .sharded import ShardedIndex, sharded_execute  # noqa: F401
