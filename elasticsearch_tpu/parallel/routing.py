"""Document→shard routing: murmur3 hash partitioning.

Replicates the reference's OperationRouting (server/src/main/java/org/
elasticsearch/cluster/routing/OperationRouting.java:245):

    shard = floorMod(murmur3(routing), num_shards)

using the same Murmur3 x86 32-bit variant as the reference's
Murmur3HashFunction (cluster/routing/Murmur3HashFunction.java) with seed 0
over the string's UTF-16-LE bytes — the reference writes two bytes per Java
char, `(byte) c` then `(byte)(c >>> 8)`, which is exactly UTF-16-LE. (The
reference additionally divides by a routingFactor when an index was
shrunk/split; routingFactor=1 here until the shrink/split APIs exist.)
"""

from __future__ import annotations


def _rotl32(x: int, r: int) -> int:
    x &= 0xFFFFFFFF
    return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF


def murmur3_hash(key: str, seed: int = 0) -> int:
    """Reference-compatible routing hash: murmur3_32 of UTF-16-LE bytes."""
    return murmur3_32(key.encode("utf-16-le"), seed)


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """Murmur3 x86_32 over raw bytes; returns signed int32 like Java."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n = len(data)
    rounded = n - (n % 4)
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = _rotl32(k, 15)
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = _rotl32(k, 15)
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    # Java int is signed.
    return h - 0x100000000 if h >= 0x80000000 else h


def shard_for_id(doc_id: str, num_shards: int) -> int:
    """floorMod(murmur3(id), num_shards), as in OperationRouting.java:245."""
    return murmur3_hash(doc_id) % num_shards
