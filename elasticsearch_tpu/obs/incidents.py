"""Incident autopsy: auto-captured, time-correlated evidence capsules
for every non-green transition (ISSUE 19).

The health report can *diagnose* live and the remediation loops can
*act*, but every signal is ephemeral: windowed metrics age out in 60s
and the trace ring churns. The IncidentService watches health-indicator
transitions (via the HealthService transition hook), remediation-loop
advisory degradation, and windowed shed/eviction bursts, and freezes an
*incident capsule* — the reference's support-diagnostics bundle analog:

- the triggering indicator's full symptom/details/impacts/diagnosis,
- flight-recorder frames spanning the pre/post windows (obs/recorder.py
  — the guarantee that evidence from *before* the trigger survives),
- cluster-wide spliced trace trees of the window's slowest exemplars
  (the PR-13 `collect_fragments` scatter / ProcCluster `_ctl` path),
- a hot-threads sample taken at capture time (local, quick),
- transport recent-events with peer names,
- every remediation action inside the window (history + the published
  `ClusterState.remediations`),

then appends a resolution record (time-to-green) when the triggering
condition recovers.

Capture is two-phase so a health poll's latency budget survives chaos:
the *freeze* (trigger, diagnosis, frames, remediation window, transport
events — pure dict assembly) happens synchronously inside the
triggering report, and the *enrichment* (trace splice fan, hot-threads
sample — the parts that cost wall clock or a wire round) fills in on a
bounded background thread. A browned-out peer can therefore never push
the triggering health poll past its fan deadline.

`ESTPU_INCIDENTS=0` disarms the service (present-but-inert stats shape,
no frames, no captures). `ESTPU_INCIDENTS_DIR` exports each capsule as
a JSON bundle on freeze and again on resolve.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

from .recorder import DEFAULT_CAPACITY, FlightRecorder

DEFAULT_RING = 32
DEFAULT_COOLDOWN_S = 60.0
# Windowed burst floors: a trailing-window shed/eviction count past
# these freezes a capsule even when every indicator still reads green
# (the burst may be absorbed before the next report interprets it).
DEFAULT_SHED_BURST = 256
DEFAULT_EVICTION_BURST = 512
# Evidence bounds: capsules are bounded artifacts, never unbounded dumps.
MAX_FRAMES_PER_CAPSULE = 60
MAX_EXEMPLAR_TRACES = 3
MAX_SPANS_PER_TRACE = 200
MAX_ACTIONS_PER_CAPSULE = 32


def _env_f(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return float(default)
    try:
        return float(raw)
    except ValueError:
        return float(default)


class IncidentService:
    """Bounded incident ring + the flight recorder feeding it.

    Wired as the HealthService transition hook on both cluster forms:
    every health report records one recorder frame and is screened for
    triggers/resolutions; the report's own verbose indicator blocks are
    reused as the captured diagnosis, so the capture adds no second fan
    to the triggering poll."""

    def __init__(self, node, metrics=None):
        self.node = node
        self.enabled = os.environ.get("ESTPU_INCIDENTS", "1") != "0"
        self.capacity = int(
            _env_f("ESTPU_INCIDENTS_CAPACITY", DEFAULT_RING)
        )
        self.cooldown_s = _env_f(
            "ESTPU_INCIDENTS_COOLDOWN_S", DEFAULT_COOLDOWN_S
        )
        self.shed_burst = int(
            _env_f("ESTPU_INCIDENTS_SHED_BURST", DEFAULT_SHED_BURST)
        )
        self.eviction_burst = int(
            _env_f(
                "ESTPU_INCIDENTS_EVICTION_BURST", DEFAULT_EVICTION_BURST
            )
        )
        self.export_dir = os.environ.get("ESTPU_INCIDENTS_DIR") or None
        self.recorder = FlightRecorder(
            capacity=int(
                _env_f("ESTPU_RECORDER_CAPACITY", DEFAULT_CAPACITY)
            ),
            metrics=metrics if self.enabled else None,
        )
        self._lock = threading.Lock()
        self._ring: list[dict] = []  # newest last, bounded
        self._open: dict[str, dict] = {}  # trigger key -> incident
        self._last_capture: dict[str, float] = {}  # key -> monotonic
        self._seq = 0
        # Re-entrancy guard: a capture's own verbose health recompute
        # must not nest another frame/capture round.
        self._tl = threading.local()
        self.metrics = metrics
        if metrics is not None:
            self._captures_c = metrics.counter(
                "estpu_incident_captures_total",
                "Incident capsules frozen (auto triggers + manual grabs)",
            )
            self._resolved_c = metrics.counter(
                "estpu_incident_resolved_total",
                "Incidents resolved (triggering condition back to green)",
            )
            metrics.gauge(
                "estpu_incident_open",
                "Incidents currently open (trigger not yet recovered)",
                fn=lambda: len(self._open),
            )
        else:
            self._captures_c = None
            self._resolved_c = None

    # ------------------------------------------------------- frame extras

    def _frame_extras(self) -> dict[str, Any]:
        """The windowed/ledger slice of one recorder frame: every number
        here is already computed by an existing instrument — assembling
        the frame is dict work, never a fan or a device call."""
        node = self.node
        extras: dict[str, Any] = {}
        rest: dict[str, Any] = {}
        for labels, window in node.metrics.windows(
            "estpu_rest_latency_recent_ms"
        ):
            snap = window.snapshot()
            if snap["count"]:
                rest[labels.get("endpoint", "_all")] = {
                    "p50_ms": round(snap["p50"], 3),
                    "p99_ms": round(snap["p99"], 3),
                    "rate_per_s": snap["rate_per_s"],
                }
        if rest:
            extras["rest_latency_recent"] = rest
        shed = 0
        window = node.metrics.window("estpu_exec_batcher_shed_recent")
        if window is not None:
            shed += int(window.count())
        for _labels, lane_w in node.metrics.windows(
            "estpu_qos_shed_recent"
        ):
            shed += int(lane_w.count())
        extras["shed_recent"] = shed
        evictions = 0
        for name in (
            "estpu_filter_cache_evictions_recent",
            "estpu_ann_evictions_recent",
        ):
            window = node.metrics.window(name)
            if window is not None:
                evictions += int(window.count())
        extras["evictions_recent"] = evictions
        breaker = node.breaker.stats()
        extras["breaker"] = {
            k: breaker[k] for k in breaker if not isinstance(breaker[k], dict)
        }
        hbm = node.hbm_ledger.snapshot()
        extras["hbm_total_bytes"] = int(hbm.get("total_bytes", 0))
        extras["qos"] = node.qos.health_inputs()
        exemplars = [
            q["trace_id"]
            for q in node.insights.queries(size=5)
            if q.get("trace_id")
        ]
        if exemplars:
            extras["exemplar_trace_ids"] = exemplars[:MAX_EXEMPLAR_TRACES]
        return extras

    # ---------------------------------------------------------- evidence

    def _transport_evidence(self) -> dict[str, Any]:
        """Transport recent-events with peer names, from the cluster
        hub's registry (whichever transport backs this topology)."""
        node = self.node
        out: dict[str, Any] = {}
        if node.replication is None:
            return out
        hub = node.replication.cluster.hub
        hub_metrics = getattr(hub, "metrics", None)
        if hub_metrics is not None:
            events = hub_metrics.window_counts(
                "estpu_transport_events_recent", "event"
            )
            if events:
                out["events_recent"] = {
                    k: int(v) for k, v in sorted(events.items())
                }
            peers: dict[str, dict[str, int]] = {}
            for labels, window in hub_metrics.windows(
                "estpu_transport_peer_events_recent"
            ):
                peer = labels.get("peer")
                if not peer:
                    continue
                event = labels.get("event", "event")
                entry = peers.setdefault(peer, {})
                entry[event] = entry.get(event, 0) + int(window.count())
            if peers:
                out["peer_events_recent"] = {
                    p: peers[p] for p in sorted(peers)
                }
        hub_stats = getattr(hub, "stats", None)
        if hub_stats is not None:
            try:
                out["stats"] = hub_stats()
            # staticcheck: ignore[broad-except] capsule evidence is best-effort: a transport mid-teardown must degrade the bundle, never fail the capture
            except Exception:
                pass
        return out

    def _remediation_window(self, since_ms: int) -> dict[str, Any]:
        """Remediation actions inside the incident window: the service's
        own recent history plus the transitions published into cluster
        state (cluster/remediation.py `_publish_transition`)."""
        node = self.node
        view = node.remediation.health_view()
        recent = [
            dict(r)
            for r in view.get("recent", ())
            if int(r.get("at_ms", 0)) >= since_ms
        ]
        published = []
        state = node._coordinator_state()
        for record in getattr(state, "remediations", None) or ():
            if int(record.get("at_ms", 0)) >= since_ms:
                published.append(dict(record))
        return {
            "actions": recent[-MAX_ACTIONS_PER_CAPSULE:],
            "published": published[-MAX_ACTIONS_PER_CAPSULE:],
            "advisory": dict(view.get("advisory", {})),
            "dry_run": bool(view.get("dry_run", False)),
        }

    def _exemplar_traces(self, since_ms: int) -> list[dict]:
        """Cluster-wide spliced span trees of the window's slowest
        exemplars: the insights ring names the trace ids, the PR-13
        scatter (or the ProcCluster `_ctl` path) splices each tree."""
        from ..node import ApiError

        node = self.node
        picked: list[dict] = []
        for entry in node.insights.queries(size=10):
            trace_id = entry.get("trace_id")
            if not trace_id:
                continue
            at_ms = int(entry.get("timestamp_ms", 0) or 0)
            if at_ms and at_ms < since_ms:
                continue
            picked.append(entry)
            if len(picked) >= MAX_EXEMPLAR_TRACES:
                break
        out: list[dict] = []
        for entry in picked:
            trace_id = entry["trace_id"]
            summary: dict[str, Any] = {
                "trace_id": trace_id,
                "took_ms": entry.get("took_ms"),
                "index": entry.get("index"),
            }
            try:
                tree = node.get_trace(trace_id)
                spans = tree.get("spans", [])
                summary["spans"] = spans[:MAX_SPANS_PER_TRACE]
                summary["span_count"] = len(spans)
                summary["nodes"] = sorted(
                    {
                        s.get("node")
                        for s in spans
                        if isinstance(s, dict) and s.get("node")
                    }
                )
                if "_nodes" in tree:
                    summary["_nodes"] = tree["_nodes"]
            except ApiError:
                summary["missing"] = "trace aged out of the ring"
            # staticcheck: ignore[broad-except] capsule evidence is best-effort: a mid-chaos trace fan failure must degrade the bundle, never fail the capture
            except Exception as e:
                summary["error"] = f"{type(e).__name__}: {e}"
            out.append(summary)
        return out

    def _hot_threads_sample(self) -> str:
        """A quick LOCAL sample (never the cluster fan: capture must not
        spend a second per-send deadline under the very chaos that
        triggered it)."""
        from .hot_threads import hot_threads_text

        return hot_threads_text(
            node_name=self.node.node_name,
            threads=3,
            interval_s=0.05,
            snapshots=2,
            metrics=self.node.metrics,
        )

    # ----------------------------------------------------------- the hook

    def on_report(
        self,
        transitions: list[dict],
        indicators: dict[str, dict],
        verbose: bool,
    ) -> None:
        """HealthService transition hook: record one recorder frame,
        screen for new triggers, resolve recovered incidents. Runs on
        every report round (the health poll IS the recorder cadence)."""
        if not self.enabled or getattr(self._tl, "capturing", False):
            return
        statuses = {
            name: result.get("status", "unknown")
            for name, result in indicators.items()
        }
        extras = self._frame_extras()
        self.recorder.record(statuses, extras)
        # --- new triggers -------------------------------------------
        for t in transitions:
            if t["to"] == "green":
                continue
            detail = indicators.get(t["indicator"]) if verbose else None
            self._maybe_capture(
                key=f"indicator:{t['indicator']}",
                trigger={
                    "kind": "indicator",
                    "indicator": t["indicator"],
                    "from": t["from"],
                    "to": t["to"],
                    "reason": (
                        f"health indicator [{t['indicator']}] went "
                        f"{t['from'] or 'unknown'} -> {t['to']}"
                    ),
                },
                detail=detail,
            )
        advisory = self.node.remediation.health_view().get("advisory", {})
        for loop, why in advisory.items():
            self._maybe_capture(
                key=f"remediation_advisory:{loop}",
                trigger={
                    "kind": "remediation_advisory",
                    "loop": loop,
                    "reason": (
                        f"remediation loop [{loop}] degraded to "
                        f"advisory: {why}"
                    ),
                },
                detail=None,
            )
        for burst, count, floor in (
            ("shed", extras.get("shed_recent", 0), self.shed_burst),
            (
                "evictions",
                extras.get("evictions_recent", 0),
                self.eviction_burst,
            ),
        ):
            if count >= floor:
                self._maybe_capture(
                    key=f"burst:{burst}",
                    trigger={
                        "kind": "burst",
                        "burst": burst,
                        "count": int(count),
                        "threshold": int(floor),
                        "reason": (
                            f"windowed {burst} burst: {int(count)} over "
                            f"the trailing window (floor {int(floor)})"
                        ),
                    },
                    detail=None,
                )
        # --- resolutions --------------------------------------------
        with self._lock:
            open_now = list(self._open.items())
        for key, incident in open_now:
            trigger = incident["trigger"]
            recovered = False
            if trigger["kind"] == "indicator":
                status = statuses.get(trigger["indicator"])
                recovered = status == "green"
            elif trigger["kind"] == "remediation_advisory":
                recovered = trigger["loop"] not in advisory
            elif trigger["kind"] == "burst":
                count = extras.get(f"{trigger['burst']}_recent", 0)
                recovered = count < trigger["threshold"] / 2
            if recovered:
                self._resolve(key, incident)

    # ----------------------------------------------------------- capture

    def _maybe_capture(
        self, key: str, trigger: dict, detail: dict | None
    ) -> dict | None:
        now = time.monotonic()
        with self._lock:
            open_incident = self._open.get(key)
            if open_incident is not None:
                # Escalation while open (yellow -> red): note it on the
                # open capsule instead of double-capturing.
                if trigger.get("to") and trigger.get("to") != (
                    open_incident["trigger"].get("to")
                ):
                    open_incident.setdefault("escalations", []).append(
                        dict(trigger)
                    )
                return None
            last = self._last_capture.get(key)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last_capture[key] = now
        return self._capture(key, trigger, detail)

    def _capture(
        self,
        key: str | None,
        trigger: dict,
        detail: dict | None,
        enrich_async: bool = True,
    ) -> dict:
        """Freeze the capsule. The synchronous half is dict assembly
        only; trace splice + hot threads enrich on a background thread
        (see the module docstring's latency-budget rationale)."""
        t0 = time.monotonic()
        # staticcheck: ignore[wallclock-duration] operator-facing timestamp, not a duration
        started_ms = int(time.time() * 1e3)
        since_ms = started_ms - 60_000  # one trailing-window span back
        if detail is None and trigger.get("indicator"):
            detail = self._indicator_detail(trigger["indicator"])
        capsule: dict[str, Any] = {
            "indicator": detail,
            "frames": self.recorder.frames(limit=MAX_FRAMES_PER_CAPSULE),
            "transport": self._transport_evidence(),
            "remediation": self._remediation_window(since_ms),
            "enrichment": "pending",
        }
        with self._lock:
            self._seq += 1
            incident: dict[str, Any] = {
                "id": f"inc-{self._seq:04d}",
                "status": "open" if key is not None else "resolved",
                "trigger": dict(trigger),
                "started_at_ms": started_ms,
                "time_to_green_ms": None,
                "capsule": capsule,
            }
            if key is not None:
                self._open[key] = incident
            self._ring.append(incident)
            self._evict_locked()
        if self._captures_c is not None:
            self._captures_c.inc()
        capsule["freeze_cost_ms"] = round(
            (time.monotonic() - t0) * 1e3, 3
        )
        if enrich_async:
            threading.Thread(
                target=self._enrich,
                args=(incident, since_ms),
                daemon=True,
                name=f"estpu-incident-{incident['id']}",
            ).start()
        else:
            self._enrich(incident, since_ms)
        return incident

    def _enrich(self, incident: dict, since_ms: int) -> None:
        capsule = incident["capsule"]
        self._tl.capturing = True
        try:
            capsule["traces"] = self._exemplar_traces(since_ms)
            capsule["hot_threads"] = self._hot_threads_sample()
            capsule["enrichment"] = "complete"
        # staticcheck: ignore[broad-except] enrichment is best-effort evidence: a mid-chaos fan error degrades the bundle (recorded on it), never crashes the capture thread silently
        except Exception as e:
            capsule["enrichment"] = f"failed: {type(e).__name__}: {e}"
        finally:
            self._tl.capturing = False
        self._export(incident)

    def _indicator_detail(self, indicator: str) -> dict | None:
        """The triggering report was terse: recompute ONE indicator
        verbosely, with the hook guard held so the recompute can never
        nest another frame/capture round."""
        self._tl.capturing = True
        try:
            report = self.node.health_report(
                verbose=True, indicator=indicator
            )
            return report["indicators"].get(indicator)
        # staticcheck: ignore[broad-except] capsule evidence is best-effort: a failed recompute degrades the bundle to the terse symptom, never fails the capture
        except Exception:
            return None
        finally:
            self._tl.capturing = False

    def _resolve(self, key: str, incident: dict) -> None:
        # staticcheck: ignore[wallclock-duration] operator-facing timestamp; the delta below is ms-vs-ms of the same clock
        resolved_ms = int(time.time() * 1e3)
        with self._lock:
            if self._open.get(key) is not incident:
                return
            del self._open[key]
            incident["status"] = "resolved"
            incident["resolved_at_ms"] = resolved_ms
            incident["time_to_green_ms"] = max(
                0, resolved_ms - incident["started_at_ms"]
            )
        # Post-window evidence: frames since the trigger and any
        # remediation actions the window picked up while open.
        capsule = incident["capsule"]
        capsule["post_frames"] = self.recorder.frames(
            since_ms=incident["started_at_ms"],
            limit=MAX_FRAMES_PER_CAPSULE,
        )
        capsule["remediation"] = self._remediation_window(
            incident["started_at_ms"] - 60_000
        )
        if self._resolved_c is not None:
            self._resolved_c.inc()
        self._export(incident)

    def _evict_locked(self) -> None:
        """Bound the ring: resolved incidents age out first; an open
        incident is only dropped when resolved ones cannot make room."""
        while len(self._ring) > self.capacity:
            victim = None
            for candidate in self._ring:
                if candidate["status"] != "open":
                    victim = candidate
                    break
            if victim is None:
                victim = self._ring[0]
                for k, v in list(self._open.items()):
                    if v is victim:
                        del self._open[k]
            self._ring.remove(victim)

    def _export(self, incident: dict) -> None:
        if self.export_dir is None:
            return
        try:
            os.makedirs(self.export_dir, exist_ok=True)
            path = os.path.join(
                self.export_dir, f"incident-{incident['id']}.json"
            )
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(incident, f, default=str)
            os.replace(tmp, path)
        except OSError as e:
            incident["export_error"] = f"{type(e).__name__}: {e}"

    # ---------------------------------------------------------- remediation

    def on_remediation_record(self, record: dict) -> None:
        """RemediationService action hook: an executed/planned action
        lands on every open capsule live (the resolve pass re-derives
        the full window anyway; this keeps mid-incident GETs honest)."""
        if not self.enabled:
            return
        with self._lock:
            for incident in self._open.values():
                actions = incident["capsule"]["remediation"].setdefault(
                    "actions", []
                )
                actions.append(dict(record))
                del actions[:-MAX_ACTIONS_PER_CAPSULE]

    # ------------------------------------------------------------ surface

    def capture(
        self, indicator: str | None = None, reason: str = "manual"
    ) -> dict:
        """POST /_incidents/_capture — an operator grab: freezes a
        capsule right now (resolved immediately: there is no trigger to
        watch). Enrichment runs synchronously — the operator asked."""
        if not self.enabled:
            return {"enabled": False, "captured": False}
        trigger: dict[str, Any] = {"kind": "manual", "reason": reason}
        if indicator is not None:
            trigger["indicator"] = indicator
        incident = self._capture(
            None, trigger, detail=None, enrich_async=False
        )
        return incident

    def incidents(self, verbose: bool = True) -> list[dict]:
        """The ring, newest first: full capsules when verbose, else
        status/trigger lines only."""
        with self._lock:
            ring = list(reversed(self._ring))
        if verbose:
            return ring
        return [self._summary(i) for i in ring]

    @staticmethod
    def _summary(incident: dict) -> dict:
        capsule = incident.get("capsule", {})
        remediation = capsule.get("remediation", {})
        return {
            "id": incident["id"],
            "status": incident["status"],
            "trigger": dict(incident["trigger"]),
            "started_at_ms": incident["started_at_ms"],
            "resolved_at_ms": incident.get("resolved_at_ms"),
            "time_to_green_ms": incident.get("time_to_green_ms"),
            "actions": len(remediation.get("actions", ())),
            "enrichment": capsule.get("enrichment"),
        }

    def get(self, incident_id: str) -> dict | None:
        with self._lock:
            for incident in self._ring:
                if incident["id"] == incident_id:
                    return incident
        return None

    def stats(self) -> dict:
        """The `_nodes/stats → incidents` section (present-but-inert
        under ESTPU_INCIDENTS=0, like every other gated subsystem)."""
        with self._lock:
            open_count = len(self._open)
            total = self._seq
            resolved = sum(
                1 for i in self._ring if i["status"] == "resolved"
            )
        return {
            "enabled": self.enabled,
            "open": open_count,
            "captured_total": total,
            "resolved_in_ring": resolved,
            "capacity": self.capacity,
            "cooldown_s": self.cooldown_s,
            "export_dir": self.export_dir,
            "recorder": self.recorder.stats(),
        }
