"""Cluster health report: rule-based indicators over rolling windows.

The interpretation layer over PRs 4/13/14's raw telemetry (the
reference's `HealthService` / `GET /_health_report`,
server/src/main/java/org/elasticsearch/health/HealthService.java): every
instrument so far answers "what happened since boot"; an operator needs
"is the cluster healthy RIGHT NOW, and if not, why and what do I do".
Each indicator computes green/yellow/red from cluster state, cumulative
counters, and the obs/metrics.py rolling windows (`estpu_*_recent`), and
renders reference-shaped `symptom` / `details` / `impacts[]` /
`diagnosis[]{cause, action}` blocks.

`INDICATORS` is the machine-checked registry (staticcheck's
registry-indicator rule, like `LEDGER_LABELS` / `CATALOG`): every entry
must have a module-level `indicator_<name>` implementation here, and
every implementation must be registered — an indicator that exists but
never renders (or renders but never computes) fails `check_static.py`.

Indicator functions are PURE over a `HealthContext`: the coordinating
front (node.py), the in-process LocalCluster fan, and the multi-process
ProcCluster supervisor each assemble a context (local inputs + per-node
`health_inputs` wire sections + named fan failures) and call ONE
`HealthService.report`, so the report shape cannot drift between
cluster forms. A dead or wedged node degrades `shards_availability` /
`master_stability` with a NAMED diagnosis inside the per-send deadline —
never a hang (the PR-13 scatter contract).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

# Machine-checked indicator registry (staticcheck registry-indicator):
# each entry maps to a module-level `indicator_<name>` function below.
INDICATORS = (
    "shards_availability",
    "master_stability",
    "device_memory",
    "device_compile",
    "exec_saturation",
    "transport",
)

_STATUS_RANK = {"green": 0, "yellow": 1, "red": 2, "unknown": 1}

# Rule thresholds (env-tunable; defaults sized for the CI/laptop shape —
# a production deployment tunes them like the reference's health node
# settings).
HBM_YELLOW_FRACTION = float(
    os.environ.get("ESTPU_HEALTH_HBM_YELLOW_FRACTION", "0.9") or 0.9
)
EVICTION_BURST = int(os.environ.get("ESTPU_HEALTH_EVICTION_BURST", "64"))
QUEUE_P99_YELLOW_MS = float(
    os.environ.get("ESTPU_HEALTH_QUEUE_P99_MS", "250") or 250
)
SHED_RED = int(os.environ.get("ESTPU_HEALTH_SHED_RED", "100"))
REELECTION_YELLOW = int(os.environ.get("ESTPU_HEALTH_REELECTIONS", "2"))
# Reconnect churn threshold: sized so ONE node death's dial blip (the
# survivors' steppers retry a refused peer a dozen-odd times before the
# routing table updates) stays under it, while a crash-looping or
# flapping peer (hundreds of dials per minute) crosses it — a single
# death is shards_availability's finding, not a wire problem.
TRANSPORT_CHURN_YELLOW = int(
    os.environ.get("ESTPU_HEALTH_TRANSPORT_CHURN", "50")
)


def worst(statuses) -> str:
    """The most severe of several statuses (green < yellow < red)."""
    out = "green"
    for status in statuses:
        if _STATUS_RANK.get(status, 1) > _STATUS_RANK[out]:
            out = "yellow" if status == "unknown" else status
    return out


def status_at_least(status: str, wanted: str) -> bool:
    """Is `status` at least as healthy as `wanted`? (green satisfies a
    yellow wait; yellow does not satisfy a green wait.)"""
    return _STATUS_RANK.get(status, 2) <= _STATUS_RANK.get(wanted, 0)


def shard_summary(state) -> dict[str, Any]:
    """Shard math + status from a published ClusterState — the ONE
    computation `GET /_cluster/health`, `_cat/health`, and the
    `shards_availability` indicator are all views of. `state=None`
    (no reachable coordinator) is red; an unassigned PRIMARY is red;
    in-sync copies below the configured replica count are yellow."""
    active_primaries = 0
    active_shards = 0
    unassigned = 0
    desired = 0
    initializing = 0
    n_nodes = 0
    red_indices: list[str] = []
    if state is not None:
        n_nodes = len(state.nodes)
        for name, meta in state.indices.items():
            for routing in meta.shards.values():
                desired += 1 + meta.n_replicas
                initializing += len(routing.recovering)
                if routing.primary is None:
                    unassigned += 1 + meta.n_replicas
                    if name not in red_indices:
                        red_indices.append(name)
                    continue
                active_primaries += 1
                active_shards += len(routing.assigned())
    if state is None or unassigned:
        status = "red"  # an unassigned PRIMARY is red, not yellow
    elif active_shards < desired:
        status = "yellow"
    else:
        status = "green"
    return {
        "status": status,
        "nodes": n_nodes,
        "active_primaries": active_primaries,
        "active_shards": active_shards,
        "unassigned_shards": unassigned,
        "desired_shards": desired,
        "initializing_shards": initializing,
        "red_indices": red_indices,
    }


@dataclass
class HealthContext:
    """Everything one report round computes from. `node_inputs` holds
    one `health_inputs`-shaped section per node (the coordinating
    front's own section included); `fan_failures` are the PR-13-style
    named `{node, type, reason}` entries for members that did not answer
    within the per-send deadline."""

    cluster_name: str = "es-tpu"
    coordinator: str = "node-0"
    standalone: bool = True
    state: Any = None  # published ClusterState (None when standalone)
    expected_nodes: tuple[str, ...] = ()
    node_inputs: dict[str, dict] = field(default_factory=dict)
    fan_failures: list[dict] = field(default_factory=list)
    fanned: bool = False
    # Indices served locally by the coordinating front (the standalone
    # shard surface the cluster state does not cover).
    local_indices: dict[str, Any] = field(default_factory=dict)
    # HealthService-observed control-plane history (recent re-elections,
    # step-error deltas) — filled by HealthService.report.
    recent_terms: int = 0
    recent_step_errors: int = 0
    # Remediation inputs (cluster/remediation.py reads the SAME context
    # the indicators render): alias -> sorted target names, the trailing
    # window's searched index names (demotion must never pick a hot
    # index), live scroll cursors (their frozen handles pin device
    # planes), and the remediation service's own recent-action view
    # (RemediationService.health_view()) for diagnosis grafting.
    aliases: dict[str, tuple] = field(default_factory=dict)
    recent_search_indices: tuple = ()
    scrolls_active: int = 0
    remediation: dict | None = None
    # Report wall-clock (plan_lifecycle's rollover-age input): filled by
    # the node when it builds the context, so planners stay clock-free.
    now: float = 0.0


def _result(
    status: str,
    symptom: str,
    details: dict | None = None,
    impacts: list | None = None,
    diagnosis: list | None = None,
) -> dict[str, Any]:
    return {
        "status": status,
        "symptom": symptom,
        "details": details or {},
        "impacts": impacts or [],
        "diagnosis": diagnosis or [],
    }


def _graft_remediation(
    indicators: dict[str, Any], ctx: HealthContext
) -> None:
    """Name the remediation loops' recent work in the indicators they
    serve (ACTION_INDICATOR maps loop -> indicator): every executed
    action, every dry-run plan, and every advisory-degraded loop lands
    in that indicator's details + diagnosis — the report narrates what
    the self-driving control plane did, not just what it saw."""
    view = ctx.remediation
    if not view:
        return
    from ..cluster.remediation import ACTION_INDICATOR

    for record in view.get("recent", []):
        name = ACTION_INDICATOR.get(record.get("loop"))
        if name is None or name not in indicators:
            continue
        entry = indicators[name]
        entry.setdefault("details", {}).setdefault(
            "remediation", []
        ).append(
            {
                "kind": record.get("kind"),
                "target": record.get("target"),
                "executed": bool(record.get("executed")),
                "dry_run": bool(record.get("dry_run")),
                "suppressed": record.get("suppressed"),
            }
        )
        diagnosis = entry.setdefault("diagnosis", [])
        if record.get("executed"):
            diagnosis.append(
                {
                    "cause": record.get("reason", ""),
                    "action": (
                        f"remediation executed [{record.get('kind')}] "
                        f"on [{record.get('target')}] — no operator "
                        "action needed"
                    ),
                }
            )
        elif record.get("dry_run") and not record.get("suppressed"):
            diagnosis.append(
                {
                    "cause": record.get("reason", ""),
                    "action": (
                        f"remediation planned [{record.get('kind')}] on "
                        f"[{record.get('target')}] but dry-run mode is "
                        "on: unset ESTPU_REMEDIATION_DRY_RUN (or POST "
                        "/_remediation {\"dry_run\": false}) to actuate"
                    ),
                }
            )
    for loop, why in (view.get("advisory") or {}).items():
        name = ACTION_INDICATOR.get(loop)
        if name is None or name not in indicators:
            continue
        indicators[name].setdefault("diagnosis", []).append(
            {
                "cause": (
                    f"remediation loop [{loop}] degraded to advisory: "
                    f"{why}"
                ),
                "action": (
                    "actuation is paused after repeated failures; "
                    "investigate the failing action (GET /_remediation) "
                    "— the loop resumes automatically after the "
                    "advisory window"
                ),
            }
        )


def _fan_failure_diagnosis(ctx: HealthContext) -> list[dict]:
    """One named diagnosis entry per node that failed the health fan —
    the 'a worker died and here is its name' block the kill -9 arc
    asserts on."""
    return [
        {
            "cause": (
                f"node [{f['node']}] did not answer the health fan "
                f"within the per-send deadline ({f['type']}: "
                f"{f['reason']})"
            ),
            "action": (
                f"restart the process serving [{f['node']}] (or remove "
                "it from the cluster); shard copies it held are being "
                "promoted/re-replicated in the meantime"
            ),
        }
        for f in ctx.fan_failures
    ]


# --------------------------------------------------------------- indicators


def indicator_shards_availability(ctx: HealthContext) -> dict[str, Any]:
    """Unassigned/under-replicated shards from the published cluster
    state; a node that failed the health fan degrades the indicator
    immediately (its copies are at risk before the control plane has
    even noticed)."""
    if ctx.standalone:
        shards = sum(
            getattr(svc, "n_shards", 1) for svc in ctx.local_indices.values()
        )
        return _result(
            "green",
            f"This node is serving all {shards} local shard(s).",
            details={
                "active_shards": shards,
                "unassigned_shards": 0,
                "topology": "standalone",
            },
        )
    summary = shard_summary(ctx.state)
    status = summary["status"]
    details = dict(summary)
    diagnosis: list[dict] = []
    impacts: list[dict] = []
    if ctx.fan_failures:
        # A dead/wedged node is at least yellow even while the routing
        # table still believes its copies: the next health round will
        # fail them, and the operator should not wait for it to learn.
        status = worst([status, "yellow"])
        diagnosis.extend(_fan_failure_diagnosis(ctx))
    if summary["red_indices"]:
        diagnosis.append(
            {
                "cause": (
                    "indices "
                    f"{summary['red_indices']} have shards with no "
                    "promotable copy (every in-sync holder is gone)"
                ),
                "action": (
                    "restart the nodes that held the in-sync copies, or "
                    "restore the indices from a snapshot"
                ),
            }
        )
        impacts.append(
            {
                "severity": 1,
                "description": (
                    "searches and writes against the red indices fail "
                    "or return partial results"
                ),
                "impact_areas": ["search", "ingest"],
            }
        )
    elif status != "green":
        impacts.append(
            {
                "severity": 2,
                "description": (
                    "reads have fewer copies to fail over to; another "
                    "node loss may lose acknowledged writes"
                ),
                "impact_areas": ["search", "deployment_management"],
            }
        )
    if status == "green":
        symptom = (
            f"This cluster has all {summary['active_shards']} shard "
            "copies available."
        )
    else:
        symptom = (
            f"{summary['unassigned_shards']} of "
            f"{summary['desired_shards']} shard copies are unavailable"
            + (
                f" ({len(ctx.fan_failures)} node(s) not responding)."
                if ctx.fan_failures
                else "."
            )
        )
    return _result(status, symptom, details, impacts, diagnosis)


def indicator_master_stability(ctx: HealthContext) -> dict[str, Any]:
    """Elected master + quorum of answering voters, recent re-elections
    (term churn inside the window), and control-plane step errors that
    are still accumulating."""
    if ctx.standalone:
        return _result(
            "green",
            "This single node is its own elected master.",
            details={"master": ctx.coordinator, "topology": "standalone"},
        )
    master = None if ctx.state is None else ctx.state.master
    term = 0 if ctx.state is None else ctx.state.term
    seeds = () if ctx.state is None else tuple(ctx.state.seed_nodes)
    answering = len(ctx.node_inputs)
    quorum = len(seeds) // 2 + 1 if seeds else 1
    details: dict[str, Any] = {
        "master": master,
        "term": term,
        "seed_nodes": list(seeds),
        "answering_nodes": answering,
        "quorum": quorum,
        "recent_reelections": ctx.recent_terms,
        "recent_step_errors": ctx.recent_step_errors,
    }
    diagnosis: list[dict] = []
    impacts: list[dict] = []
    status = "green"
    if master is None:
        status = "red"
        diagnosis.append(
            {
                "cause": "no elected master is published",
                "action": (
                    "restart enough master-eligible nodes to reach "
                    f"quorum ({quorum} of {len(seeds)})"
                ),
            }
        )
    if ctx.fanned and answering < quorum:
        status = "red"
        diagnosis.extend(_fan_failure_diagnosis(ctx))
        diagnosis.append(
            {
                "cause": (
                    f"only {answering} of {len(seeds)} voters answered "
                    f"the health fan — below the election quorum of "
                    f"{quorum}"
                ),
                "action": "restart the unreachable voting nodes",
            }
        )
    elif ctx.fan_failures and status == "green":
        status = "yellow"
        diagnosis.extend(_fan_failure_diagnosis(ctx))
    if ctx.recent_terms >= REELECTION_YELLOW and status == "green":
        status = "yellow"
        diagnosis.append(
            {
                "cause": (
                    f"the master term changed {ctx.recent_terms} times "
                    "in the trailing window (election churn)"
                ),
                "action": (
                    "check inter-node connectivity and GC/CPU "
                    "starvation on the master-eligible nodes"
                ),
            }
        )
    if ctx.recent_step_errors > 0 and status == "green":
        status = "yellow"
        diagnosis.append(
            {
                "cause": (
                    f"{ctx.recent_step_errors} control-plane step "
                    "error(s) were swallowed by the background stepper "
                    "since the last report"
                ),
                "action": (
                    "inspect estpu_cluster_step_errors_total per node "
                    "and the stepper logs"
                ),
            }
        )
    if status == "red":
        impacts.append(
            {
                "severity": 1,
                "description": (
                    "the cluster cannot commit metadata changes, "
                    "promote primaries, or heal failed copies"
                ),
                "impact_areas": ["cluster_coordination", "ingest"],
            }
        )
    elif status == "yellow":
        impacts.append(
            {
                "severity": 3,
                "description": (
                    "control-plane reactions (promotion, recovery) may "
                    "lag behind failures"
                ),
                "impact_areas": ["cluster_coordination"],
            }
        )
    symptom = (
        f"The elected master is [{master}] (term {term})."
        if status == "green"
        else (
            "No elected master."
            if master is None
            else f"Master [{master}] is elected but unstable."
        )
    )
    return _result(status, symptom, details, impacts, diagnosis)


def indicator_device_memory(ctx: HealthContext) -> dict[str, Any]:
    """HBM ledger vs breaker budget: accounting drift is ALWAYS red
    (the consistency law is broken — nothing downstream of it can be
    trusted), near-budget usage / recent breaker trips / eviction
    bursts are yellow."""
    worst_status = "green"
    symptoms: list[str] = []
    details: dict[str, Any] = {"nodes": {}}
    impacts: list[dict] = []
    diagnosis: list[dict] = []
    reporting = 0
    for node_id, inputs in sorted(ctx.node_inputs.items()):
        breaker = inputs.get("breaker")
        hbm = inputs.get("hbm")
        if breaker is None and hbm is None:
            continue
        reporting += 1
        node_detail: dict[str, Any] = {}
        status = "green"
        drift = int((hbm or {}).get("breaker_drift_bytes", 0) or 0)
        node_detail["breaker_drift_bytes"] = drift
        if drift != 0:
            status = "red"
            symptoms.append(
                f"HBM accounting drift of {drift} bytes on [{node_id}]"
            )
            diagnosis.append(
                {
                    "cause": (
                        f"breaker and ledger accounting diverge by "
                        f"{drift} bytes on [{node_id}] — a device "
                        "allocation bypassed the write-through ledger"
                    ),
                    "action": (
                        "this is a bug: capture `/_cat/hbm` and "
                        "`_nodes/stats → device.hbm` and file it; "
                        "restart the node to re-zero the accounting"
                    ),
                }
            )
        if breaker is not None:
            limit = int(breaker.get("limit_size_in_bytes", 0) or 0)
            used = int(breaker.get("estimated_size_in_bytes", 0) or 0)
            node_detail["breaker_used_bytes"] = used
            node_detail["breaker_limit_bytes"] = limit
            fraction = (used / limit) if limit else 0.0
            node_detail["used_fraction"] = round(fraction, 4)
            if limit and fraction >= HBM_YELLOW_FRACTION:
                status = worst([status, "yellow"])
                symptoms.append(
                    f"[{node_id}] is at {fraction:.0%} of its HBM "
                    "breaker budget"
                )
                diagnosis.append(
                    {
                        "cause": (
                            f"device memory on [{node_id}] is within "
                            f"{1 - HBM_YELLOW_FRACTION:.0%} of the "
                            "breaker limit — one eviction burst from "
                            "breaker trips"
                        ),
                        "action": (
                            "shrink the filter/ANN cache budgets, "
                            "delete or shrink indices, or raise "
                            "ESTPU_HBM_LIMIT_BYTES"
                        ),
                    }
                )
        trips_recent = int(inputs.get("breaker_trips_recent", 0) or 0)
        node_detail["breaker_trips_recent"] = trips_recent
        if trips_recent:
            status = worst([status, "yellow"])
            symptoms.append(
                f"{trips_recent} breaker trip(s) on [{node_id}] in the "
                "trailing window"
            )
            diagnosis.append(
                {
                    "cause": (
                        f"the HBM breaker on [{node_id}] refused "
                        f"{trips_recent} allocation(s) recently "
                        "(callers saw 429 circuit_breaking_exception)"
                    ),
                    "action": (
                        "free device memory (POST /_cache/clear, delete "
                        "indices) or raise the breaker limit"
                    ),
                }
            )
        evictions = inputs.get("evictions_recent") or {}
        total_evictions = int(sum(evictions.values()))
        node_detail["evictions_recent"] = evictions
        if total_evictions >= EVICTION_BURST:
            status = worst([status, "yellow"])
            symptoms.append(
                f"eviction burst on [{node_id}]: {total_evictions} "
                "cache planes dropped in the trailing window"
            )
            diagnosis.append(
                {
                    "cause": (
                        f"{total_evictions} filter/ANN cache evictions "
                        f"on [{node_id}] in the trailing window — the "
                        "working set is thrashing its HBM budget"
                    ),
                    "action": (
                        "raise ESTPU_FILTER_CACHE_BYTES / "
                        "ESTPU_ANN_BYTES or reduce the distinct-filter "
                        "working set"
                    ),
                }
            )
        details["nodes"][node_id] = node_detail
        worst_status = worst([worst_status, status])
    if not reporting:
        return _result(
            "green",
            "No node reported device-memory inputs (device "
            "observability disabled or worker-only sections).",
            details={"enabled": False},
        )
    if worst_status != "green":
        impacts.append(
            {
                "severity": 1 if worst_status == "red" else 2,
                "description": (
                    "device-memory accounting is broken"
                    if worst_status == "red"
                    else "new segment uploads and cache admissions may "
                    "be refused with 429s"
                ),
                "impact_areas": ["search", "ingest"],
            }
        )
    symptom = (
        "Device memory is within budget on every reporting node."
        if worst_status == "green"
        else "; ".join(symptoms) + "."
    )
    return _result(worst_status, symptom, details, impacts, diagnosis)


def indicator_device_compile(ctx: HealthContext) -> dict[str, Any]:
    """The retrace census (PR 14): any steady-state retrace — a REAL XLA
    compile on a plan key's non-first launch — is yellow, with the
    offending plan classes NAMED. A recompile-per-launch silently
    multiplies p50 long before anyone reads a profile."""
    retraced: dict[str, int] = {}
    compiles_total = 0
    launch_errors = 0
    reporting = 0
    for node_id, inputs in sorted(ctx.node_inputs.items()):
        census = inputs.get("device_compile")
        if census is None:
            continue
        reporting += 1
        compiles_total += int(
            sum(census.get("compiles_by_plan_class", {}).values())
        )
        for cls, n in (census.get("retraced_plan_classes") or {}).items():
            retraced[cls] = retraced.get(cls, 0) + int(n)
        # {backend: {"ok": n, "error": n}} over the trailing window.
        outcomes = inputs.get("launch_outcomes_recent") or {}
        launch_errors += int(
            sum(entry.get("error", 0) for entry in outcomes.values())
        )
    if not reporting:
        return _result(
            "green",
            "No node reported compile-census inputs (device "
            "observability disabled).",
            details={"enabled": False},
        )
    details = {
        "compiles_total": compiles_total,
        "retraced_plan_classes": {
            k: retraced[k] for k in sorted(retraced)
        },
        "launch_errors_recent": launch_errors,
    }
    symptoms: list[str] = []
    impacts: list[dict] = []
    diagnosis: list[dict] = []
    status = "green"
    if launch_errors:
        # Recent launches RAISED (the outcome="error" window): the
        # device path is failing right now, not just recompiling.
        status = "yellow"
        symptoms.append(
            f"{launch_errors} kernel launch(es) failed in the trailing "
            "window"
        )
        impacts.append(
            {
                "severity": 2,
                "description": (
                    "failing launches fall back to slower paths or "
                    "surface as shard failures"
                ),
                "impact_areas": ["search"],
            }
        )
        diagnosis.append(
            {
                "cause": (
                    "device kernel launches are raising "
                    "(estpu_device_launch_recent{outcome=\"error\"})"
                ),
                "action": (
                    "check the mesh circuit-breaker last_error and the "
                    "trace ring for the failing plan class"
                ),
            }
        )
    if retraced:
        status = "yellow"
        classes = ", ".join(sorted(retraced))
        symptoms.append(
            f"plan class(es) [{classes}] are recompiling in steady "
            f"state ({sum(retraced.values())} retrace(s))"
        )
        impacts.append(
            {
                "severity": 2,
                "description": (
                    "every retracing launch pays XLA compile latency "
                    "instead of serving — p50 inflates silently"
                ),
                "impact_areas": ["search"],
            }
        )
        diagnosis.append(
            {
                "cause": (
                    f"plan key(s) of [{classes}] fail to capture a "
                    "varying input shape, so XLA re-traces on launches "
                    "after the first"
                ),
                "action": (
                    "add the varying dimension to the plan key (or pad "
                    "it to a fixed bucket); confirm with POST "
                    "/_profiler/start and estpu_device_retraces_total"
                ),
            }
        )
    if status == "green":
        return _result(
            "green",
            "No steady-state retraces: every plan class compiled once "
            "and stayed compiled.",
            details,
        )
    symptom = "; ".join(symptoms) + "."
    return _result(status, symptom, details, impacts, diagnosis)


def indicator_exec_saturation(ctx: HealthContext) -> dict[str, Any]:
    """Micro-batcher admission health over the trailing window: queue
    waits, 429 shed rate, quarantined groups. Cumulative shed counts are
    history; the windows say whether clients are being turned away
    NOW."""
    reporting = 0
    status = "green"
    symptoms: list[str] = []
    diagnosis: list[dict] = []
    details: dict[str, Any] = {"nodes": {}}
    for node_id, inputs in sorted(ctx.node_inputs.items()):
        batcher = inputs.get("batcher")
        if batcher is None:
            continue
        reporting += 1
        if batcher.get("enabled") is False:
            details["nodes"][node_id] = {"enabled": False}
            continue
        recent = inputs.get("queue_wait_recent") or {}
        shed_recent = int(inputs.get("shed_recent", 0) or 0)
        quarantined = int(batcher.get("quarantined_now", 0) or 0)
        node_detail = {
            "queue_wait_recent_p99_ms": recent.get("p99", 0.0),
            "queue_wait_recent_count": recent.get("count", 0),
            "shed_recent": shed_recent,
            "quarantined_now": quarantined,
            "queued_now": batcher.get("queued", 0),
        }
        # Per-tenant QoS attribution: when weighted shedding engages,
        # NAME the over-quota lanes — "who is being turned away" is the
        # question the operator actually asks.
        qos = inputs.get("qos") or {}
        shed_by_lane = qos.get("shed_recent_by_lane") or {}
        if shed_by_lane:
            node_detail["shed_recent_by_lane"] = shed_by_lane
        lane_p99 = qos.get("queue_wait_p99_ms_by_lane") or {}
        if lane_p99:
            node_detail["queue_wait_p99_ms_by_lane"] = lane_p99
        top_shed = ", ".join(
            f"[{lane}]={int(n)}" for lane, n in list(shed_by_lane.items())[:3]
        )
        details["nodes"][node_id] = node_detail
        if shed_recent >= SHED_RED:
            status = "red"
            symptoms.append(
                f"[{node_id}] shed {shed_recent} searches with 429 in "
                "the trailing window"
                + (f" (top shed tenants: {top_shed})" if top_shed else "")
            )
            diagnosis.append(
                {
                    "cause": (
                        f"the batch queue on [{node_id}] is full and "
                        "shedding load at a sustained rate"
                        + (
                            f"; weighted shedding is rejecting "
                            f"over-quota tenants {top_shed}"
                            if top_shed
                            else ""
                        )
                    ),
                    "action": (
                        "add serving capacity, raise the queue limit, "
                        "throttle the named tenants (ESTPU_QOS_WEIGHTS "
                        "re-weights their lanes), or shed at the client "
                        "with the Retry-After hints"
                    ),
                }
            )
        elif shed_recent:
            status = worst([status, "yellow"])
            symptoms.append(
                f"[{node_id}] shed {shed_recent} search(es) recently"
                + (f" (top shed tenants: {top_shed})" if top_shed else "")
            )
            diagnosis.append(
                {
                    "cause": (
                        f"the batch queue on [{node_id}] filled and "
                        f"shed {shed_recent} request(s) in the "
                        "trailing window"
                        + (
                            f"; over-quota tenants: {top_shed}"
                            if top_shed
                            else ""
                        )
                    ),
                    "action": (
                        "watch estpu_exec_batcher_shed_recent and "
                        "estpu_qos_shed_recent; if it sustains, add "
                        "capacity, raise queue_limit, or re-weight the "
                        "named lanes via ESTPU_QOS_WEIGHTS"
                    ),
                }
            )
        p99 = float(recent.get("p99", 0.0) or 0.0)
        if p99 >= QUEUE_P99_YELLOW_MS:
            status = worst([status, "yellow"])
            symptoms.append(
                f"queue-wait p99 on [{node_id}] is {p99:.0f}ms"
            )
            diagnosis.append(
                {
                    "cause": (
                        f"searches on [{node_id}] wait {p99:.0f}ms p99 "
                        "in the batch queue (threshold "
                        f"{QUEUE_P99_YELLOW_MS:.0f}ms)"
                    ),
                    "action": (
                        "check for a slow plan class hogging launches "
                        "(estpu_launch_ms) or lower "
                        "ESTPU_EXEC_BATCH_WAIT_MS"
                    ),
                }
            )
        if quarantined:
            status = worst([status, "yellow"])
            symptoms.append(
                f"{quarantined} group(s) quarantined on [{node_id}]"
            )
            diagnosis.append(
                {
                    "cause": (
                        f"{quarantined} batch group(s) on [{node_id}] "
                        "keep failing coalesced launches and are "
                        "serving per-request"
                    ),
                    "action": (
                        "inspect exec.batcher retried_individually and "
                        "the failing group's plan class"
                    ),
                }
            )
    if not reporting:
        return _result(
            "green",
            "No node reported batcher inputs.",
            details={"enabled": False},
        )
    impacts = []
    if status != "green":
        impacts.append(
            {
                "severity": 1 if status == "red" else 2,
                "description": (
                    "search clients are being rejected with 429s"
                    if status == "red"
                    else "search tail latency is inflated by queue "
                    "pressure"
                ),
                "impact_areas": ["search"],
            }
        )
    symptom = (
        "The execution queue is keeping up: no recent sheds, queue "
        "waits within budget."
        if status == "green"
        else "; ".join(symptoms) + "."
    )
    return _result(status, symptom, details, impacts, diagnosis)


def indicator_transport(ctx: HealthContext) -> dict[str, Any]:
    """Node-to-node wire health over the trailing window: reconnect
    churn, handshake rejects (misconfigured peer), send timeouts, plus
    the SPMD mesh circuit-breaker state on the serving front."""
    status = "green"
    symptoms: list[str] = []
    diagnosis: list[dict] = []
    details: dict[str, Any] = {"nodes": {}}
    for node_id, inputs in sorted(ctx.node_inputs.items()):
        transport = inputs.get("transport") or {}
        recent = inputs.get("transport_events_recent") or {}
        node_detail = {
            "kind": transport.get("kind"),
            "send_timeouts_total": transport.get("send_timeouts", 0),
            "reconnects_total": transport.get("reconnects", 0),
            "handshake_rejects_total": transport.get(
                "handshake_rejects", 0
            ),
            "recent_events": recent,
        }
        peer_timeouts = {
            str(peer): int(count)
            for peer, count in (
                transport.get("peer_send_timeouts_recent") or {}
            ).items()
            if int(count)
        }
        if peer_timeouts:
            node_detail["peer_send_timeouts_recent"] = peer_timeouts
        details["nodes"][node_id] = node_detail
        timeouts = int(recent.get("send_timeout", 0) or 0)
        rejects = int(recent.get("handshake_reject", 0) or 0)
        reconnects = int(recent.get("reconnect", 0) or 0)
        if peer_timeouts:
            # Per-peer attribution (the brownout diagnosis): the windowed
            # per-peer twins say WHO is not answering within the per-send
            # deadline, not just that someone isn't.
            status = worst([status, "yellow"])
            for peer, count in sorted(peer_timeouts.items()):
                symptoms.append(
                    f"peer [{peer}] timed out {count} send(s) from "
                    f"[{node_id}] in the trailing window"
                )
                diagnosis.append(
                    {
                        "cause": (
                            f"sends from [{node_id}] to peer [{peer}] "
                            f"exceeded the per-send deadline {count} "
                            f"time(s) in the trailing window — [{peer}] "
                            "is slow, wedged, or partitioned (brownout)"
                        ),
                        "action": (
                            f"check the process serving [{peer}] and its "
                            "network path; adaptive replica selection "
                            "routes reads around it in the meantime"
                        ),
                    }
                )
        elif timeouts:
            status = worst([status, "yellow"])
            symptoms.append(
                f"{timeouts} send timeout(s) at [{node_id}] in the "
                "trailing window"
            )
            diagnosis.append(
                {
                    "cause": (
                        f"sends from [{node_id}] exceeded the per-send "
                        "deadline recently — a peer is dead, wedged, or "
                        "partitioned"
                    ),
                    "action": (
                        "check the peer processes and network; `GET "
                        "/_nodes/stats` names which fans failed"
                    ),
                }
            )
        if rejects:
            status = worst([status, "yellow"])
            symptoms.append(
                f"{rejects} handshake reject(s) at [{node_id}]"
            )
            diagnosis.append(
                {
                    "cause": (
                        f"[{node_id}] refused transport handshakes "
                        "(cluster-name/protocol-version mismatch)"
                    ),
                    "action": (
                        "a foreign or mis-versioned process is dialing "
                        "this cluster; align cluster_name/versions"
                    ),
                }
            )
        if reconnects >= TRANSPORT_CHURN_YELLOW:
            status = worst([status, "yellow"])
            symptoms.append(
                f"reconnect churn at [{node_id}]: {reconnects} dial "
                "retries in the trailing window"
            )
            diagnosis.append(
                {
                    "cause": (
                        f"[{node_id}] re-dialed peers {reconnects} "
                        "times in the trailing window — flapping "
                        "connectivity"
                    ),
                    "action": (
                        "check for a crash-looping peer or packet loss "
                        "between hosts"
                    ),
                }
            )
    mesh = {}
    for node_id, inputs in sorted(ctx.node_inputs.items()):
        for index, state in (inputs.get("mesh_breakers") or {}).items():
            mesh[index] = state
            if state not in ("closed",):
                status = worst([status, "yellow"])
                symptoms.append(
                    f"mesh circuit breaker for [{index}] is [{state}]"
                )
                diagnosis.append(
                    {
                        "cause": (
                            f"the SPMD mesh path for [{index}] is "
                            f"[{state}]: recent execution failures "
                            "tripped its circuit breaker"
                        ),
                        "action": (
                            "serving continues on the host path; see "
                            "mesh_serving.views[...].last_error and "
                            "re-enable after fixing the cause"
                        ),
                    }
                )
    if mesh:
        details["mesh_breakers"] = mesh
    # Membership view (the partition diagnosis): an expected member the
    # elected master has dropped from the published state is unreachable
    # from the majority — name it. Guarded on an elected master so a
    # cluster still bootstrapping (empty membership, no master) reports
    # through master_stability instead of a spurious wire diagnosis.
    if ctx.state is not None and ctx.expected_nodes:
        members = set(getattr(ctx.state, "nodes", ()) or ())
        master = getattr(ctx.state, "master", None)
        missing = [
            n
            for n in ctx.expected_nodes
            if n not in members and n != master
        ]
        if missing and master is not None and members:
            status = worst([status, "yellow"])
            details["unreachable_members"] = missing
            for node_id in missing:
                symptoms.append(
                    f"expected member [{node_id}] is not in the "
                    "published cluster state"
                )
                diagnosis.append(
                    {
                        "cause": (
                            f"expected member [{node_id}] is missing "
                            f"from the state published by master "
                            f"[{master}] (term "
                            f"{getattr(ctx.state, 'term', '?')}): the "
                            "master cannot reach it — it is dead or on "
                            "the minority side of a partition"
                        ),
                        "action": (
                            f"check the process serving [{node_id}] and "
                            "the network between it and the master; "
                            "heal the partition (or restart it) and "
                            "wait for status green"
                        ),
                    }
                )
    if (
        ctx.fanned
        and ctx.expected_nodes
        and not ctx.node_inputs.keys() & set(ctx.expected_nodes)
    ):
        status = "red"
        symptoms.append("no cluster member answered the health fan")
        diagnosis.extend(_fan_failure_diagnosis(ctx))
    impacts = []
    if status != "green":
        impacts.append(
            {
                "severity": 1 if status == "red" else 3,
                "description": (
                    "the cluster wire is down"
                    if status == "red"
                    else "cross-node requests may retry or fail over "
                    "more than usual"
                ),
                "impact_areas": ["cluster_coordination", "search"],
            }
        )
    symptom = (
        "Transport is quiet: no recent timeouts, rejects, or reconnect "
        "churn."
        if status == "green"
        else "; ".join(symptoms) + "."
    )
    return _result(status, symptom, details, impacts, diagnosis)


# ------------------------------------------------------------ the service


class HealthService:
    """Stateful report builder: computes every `INDICATORS` entry over a
    HealthContext, tracks cross-report control-plane history (term
    changes for the re-election rule, step-error deltas), and surfaces
    `estpu_health_reports_total` / `estpu_health_status{indicator}` plus
    the `_nodes/stats → health` section."""

    def __init__(self, metrics=None, window_s: float = 60.0):
        self.metrics = metrics
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        # (monotonic, term) observations — re-election rule input.
        self._terms: deque[tuple[float, int]] = deque(maxlen=32)
        self._last_step_errors: dict[str, int] = {}
        self._last: dict[str, str] = {}
        self._reports = 0
        # Transition hook (obs/incidents.py): called after every report
        # with the round's status changes — the incident auto-capture
        # and flight-recorder cadence. Invoked outside the lock; a hook
        # error must never break a health report.
        self.transition_hook = None
        if metrics is not None:
            self._reports_c = metrics.counter(
                "estpu_health_reports_total",
                "Health reports computed (GET /_health_report rounds)",
            )
        else:
            self._reports_c = None

    # ------------------------------------------------ history observation

    def _observe(self, ctx: HealthContext) -> None:
        """Fold this round's control-plane observations into the rolling
        history and stamp the ctx with the recent-window aggregates."""
        now = time.monotonic()
        term = 0 if ctx.state is None else int(ctx.state.term)
        step_delta = 0
        with self._lock:
            if term and (
                not self._terms or self._terms[-1][1] != term
            ):
                self._terms.append((now, term))
            floor = now - self.window_s
            recent_terms = max(
                0,
                len([1 for t, _ in self._terms if t >= floor]) - 1,
            )
            for node_id, inputs in ctx.node_inputs.items():
                errors = int(inputs.get("step_errors", 0) or 0)
                prev = self._last_step_errors.get(node_id)
                if prev is not None and errors > prev:
                    step_delta += errors - prev
                self._last_step_errors[node_id] = errors
        ctx.recent_terms = recent_terms
        ctx.recent_step_errors = step_delta

    # --------------------------------------------------------- reporting

    def report(
        self,
        ctx: HealthContext,
        verbose: bool = True,
        indicator: str | None = None,
    ) -> dict[str, Any]:
        """Compute the full report. `verbose=False` is the cheap
        liveness-probe shape: indicator statuses + symptoms only, no
        details/impacts/diagnosis blocks (the caller also skips the
        cluster fan for it). `indicator` filters to one entry."""
        if indicator is not None and indicator not in INDICATORS:
            raise KeyError(indicator)
        self._observe(ctx)
        names = (indicator,) if indicator else INDICATORS
        indicators: dict[str, Any] = {}
        for name in names:
            result = globals()[f"indicator_{name}"](ctx)
            if not verbose:
                result = {
                    "status": result["status"],
                    "symptom": result["symptom"],
                }
            indicators[name] = result
        if verbose:
            _graft_remediation(indicators, ctx)
        status = worst(r["status"] for r in indicators.values())
        transitions: list[dict[str, Any]] = []
        with self._lock:
            self._reports += 1
            for name, result in indicators.items():
                old = self._last.get(name)
                new = result["status"]
                if old != new:
                    transitions.append(
                        {"indicator": name, "from": old, "to": new}
                    )
                self._last[name] = new
        if self._reports_c is not None:
            self._reports_c.inc()
        if self.metrics is not None:
            for name, result in indicators.items():
                self.metrics.gauge(
                    "estpu_health_status",
                    "Last-computed indicator status (0 green / 1 "
                    "yellow / 2 red)",
                    indicator=name,
                ).set(_STATUS_RANK.get(result["status"], 1))
        if self.transition_hook is not None:
            try:
                self.transition_hook(transitions, indicators, verbose)
            # staticcheck: ignore[broad-except] the hook is evidence capture — it must never break the health report it observes
            except Exception:
                pass
        out: dict[str, Any] = {
            "cluster_name": ctx.cluster_name,
            "status": status,
            "indicators": indicators,
        }
        if ctx.fanned:
            header: dict[str, Any] = {
                "total": 1 + len(ctx.expected_nodes),
                "successful": 1
                + len(
                    [
                        n
                        for n in ctx.expected_nodes
                        if n in ctx.node_inputs
                    ]
                ),
                "failed": len(ctx.fan_failures),
            }
            if ctx.fan_failures:
                header["failures"] = list(ctx.fan_failures)
            out["_nodes"] = header
        return out

    def stats(self) -> dict[str, Any]:
        """The `_nodes/stats → health` section: last statuses + rounds."""
        with self._lock:
            last = dict(self._last)
            reports = self._reports
        return {
            "reports_total": reports,
            "last_status": worst(last.values()) if last else "unknown",
            "indicators": {k: last[k] for k in sorted(last)},
        }
