"""End-to-end distributed tracing for the TPU query path.

One search produces ONE span tree: the REST root span (rest/server.py),
the gateway hop (cluster/gateway.py), per-copy transport sends
(cluster/transport.py — context rides the payload so spans from remote
ClusterNode shard executions parent correctly), per-shard scoring passes
(search/coordinator.py), planner decisions (exec/planner via tagged
events), micro-batcher queue-wait + coalesced-launch spans
(exec/batcher.py, shared across batchmates via a common launch_id), and
per-segment XLA launches (search/service.py). The granularity is the
kernel launch — an XLA program is not interruptible or observable inside,
so one segment's launch is one leaf span, the same boundary
common/tasks.py polls cancellation at.

The reference's shape for this triad is TaskManager.java (what is
running), `index.search.slowlog.*` (what was slow) and the search profile
API (where the time went); this module is the substrate all three read
from here.

Propagation is via ``contextvars`` inside a process (REST handler threads,
the in-process transport hub) plus explicit wire context: the REST edge
accepts/returns W3C ``traceparent`` (and tags ``X-Opaque-Id``), and
transport sends attach ``{"_trace": {trace_id, parent}}`` to the payload
so the receiving node re-activates the caller's context exactly as a
cross-host transport would.

Finished traces land in a bounded ring buffer (ESTPU_TRACE_BUFFER, default
256) served by `GET /_traces[/{trace_id}]`; ``?format=chrome`` renders
Chrome trace-event JSON loadable in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any

# (trace_id, span_id) of the active span on this thread/context.
_CURRENT: contextvars.ContextVar[tuple[str, str] | None] = (
    contextvars.ContextVar("estpu_trace_ctx", default=None)
)


def _new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars, the W3C traceparent width


# Span ids are HOT (several per search): a random per-process prefix + a
# GIL-atomic counter gives unique 16-hex ids at ~15x less cost than a
# uuid4 per span (measured ~5us each — a third of the whole span budget).
_SPAN_ID_PREFIX = uuid.uuid4().hex[:8]
_SPAN_ID_COUNTER = itertools.count(1)


def _new_span_id() -> str:
    return f"{_SPAN_ID_PREFIX}{next(_SPAN_ID_COUNTER) & 0xFFFFFFFF:08x}"


def parse_traceparent(value: str | None) -> tuple[str, str] | None:
    """W3C `traceparent: 00-<trace32hex>-<span16hex>-<flags>` →
    (trace_id, parent_span_id), or None on anything malformed (a broken
    header must start a fresh trace, never crash the request)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    _version, trace_id, span_id, _flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


@dataclass(slots=True)
class Span:
    """One timed node of a trace tree."""

    trace_id: str
    span_id: str
    parent_id: str | None
    name: str
    start_ms: float  # epoch millis (display)
    start_mono: float  # monotonic seconds (duration math)
    duration_ms: float | None = None  # None while open
    tags: dict[str, Any] = field(default_factory=dict)
    events: list[dict[str, Any]] = field(default_factory=list)
    status: str = "ok"  # ok | error

    def finish(self, end_mono: float | None = None) -> None:
        end = time.monotonic() if end_mono is None else end_mono
        self.duration_ms = max(0.0, (end - self.start_mono) * 1e3)

    def add_event(self, name: str, **attrs: Any) -> None:
        self.events.append(
            {
                "name": name,
                # staticcheck: ignore[wallclock-duration] user-facing event epoch timestamp in the trace export, not a duration
                "timestamp_ms": time.time() * 1e3,
                **attrs,
            }
        )

    def record_error(self, exc: BaseException) -> None:
        self.status = "error"
        self.tags["error_type"] = type(exc).__name__
        self.tags["error_reason"] = str(exc)[:200]
        # Fault-injected errors (faults/registry.py marks them) tag their
        # enclosing span so chaos runs produce readable traces.
        if getattr(exc, "injected", False):
            self.tags["injected_fault"] = True

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_time_in_millis": int(self.start_ms),
            # Sub-millisecond start for renderers (chrome_trace): spans
            # fanned within one millisecond must keep their real order.
            "start_ms": round(self.start_ms, 3),
            "duration_ms": (
                round(self.duration_ms, 3)
                if self.duration_ms is not None
                # Live export (`profile: true` inlines the still-open
                # request trace): honest elapsed-so-far, flagged.
                else round((time.monotonic() - self.start_mono) * 1e3, 3)
            ),
            "status": self.status,
        }
        if self.duration_ms is None:
            out["in_progress"] = True
        if self.tags:
            out["tags"] = dict(self.tags)
        if self.events:
            out["events"] = list(self.events)
        return out


class _SpanHandle:
    """Context manager for one span: activates it, finishes it, records
    errors without swallowing them, and (optionally) mirrors the span name
    onto a Task so `GET /_tasks` can show what a task is doing now."""

    __slots__ = ("tracer", "span", "_token", "_task", "_prev_task_span")

    def __init__(self, tracer: "Tracer", span: Span | None, task=None):
        self.tracer = tracer
        self.span = span
        self._token = None
        self._task = task
        self._prev_task_span = None

    def __enter__(self) -> Span | None:
        if self.span is not None:
            self._token = _CURRENT.set(
                (self.span.trace_id, self.span.span_id)
            )
            if self._task is not None:
                self._prev_task_span = getattr(self._task, "span_name", None)
                self._task.span_name = self.span.name
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self.span is not None:
            if exc is not None:
                self.span.record_error(exc)
            self.span.finish()
            if self._token is not None:
                _CURRENT.reset(self._token)
            if self._task is not None:
                self._task.span_name = self._prev_task_span
            self.tracer._on_span_closed(self.span)
        return False  # never swallow


class Tracer:
    """Thread-safe span collector with a bounded ring of finished traces.

    Spans are cheap no-ops when no trace is active on the calling context
    (``span()`` returns a dummy handle), so instrumented hot paths pay one
    ContextVar read when untraced."""

    def __init__(self, max_traces: int | None = None):
        if max_traces is None:
            max_traces = int(os.environ.get("ESTPU_TRACE_BUFFER", 256) or 256)
        self.max_traces = max(1, max_traces)
        self._lock = threading.Lock()
        # trace_id -> {span_id -> Span}: spans of traces still in flight.
        self._active: dict[str, dict[str, Span]] = {}
        # trace_id of each active trace's ROOT span (finishing it seals
        # the trace into the ring).
        self._roots: dict[str, str] = {}
        self._ring: deque[tuple[str, list[Span]]] = deque(
            maxlen=self.max_traces
        )
        self._index: dict[str, list[Span]] = {}

    # --------------------------------------------------------- span entry

    def context(self) -> tuple[str, str] | None:
        """(trace_id, span_id) of the active span, or None. This is the
        wire context transport sends attach to their payloads."""
        return _CURRENT.get()

    def current_trace_id(self) -> str | None:
        ctx = _CURRENT.get()
        return None if ctx is None else ctx[0]

    def start_trace(
        self,
        name: str,
        traceparent: str | None = None,
        task=None,
        **tags: Any,
    ) -> _SpanHandle:
        """Open a ROOT span (new trace, or continuing an inbound W3C
        traceparent). Finishing the root seals the trace into the ring."""
        parent = parse_traceparent(traceparent)
        trace_id = parent[0] if parent else _new_trace_id()
        span = Span(
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_id=parent[1] if parent else None,
            name=name,
            # staticcheck: ignore[wallclock-duration] user-facing span start epoch timestamp; durations come from start_mono
            start_ms=time.time() * 1e3,
            start_mono=time.monotonic(),
            tags=dict(tags),
        )
        with self._lock:
            self._active.setdefault(trace_id, {})[
                span.span_id
            ] = span
            self._roots.setdefault(trace_id, span.span_id)
        return _SpanHandle(self, span, task=task)

    def span(
        self, name: str, root: bool = False, task=None, **tags: Any
    ) -> _SpanHandle:
        """Open a child of the context's active span. With no active trace:
        a no-op handle, unless ``root=True`` which starts a new trace (the
        entry points — REST dispatch, Node.search — use root=True so every
        request is traced even off the HTTP path)."""
        ctx = _CURRENT.get()
        if ctx is None:
            if not root:
                return _SpanHandle(self, None)
            return self.start_trace(name, task=task, **tags)
        return self.span_from(ctx, name, task=task, **tags)

    def span_from(
        self, ctx: tuple[str, str], name: str, task=None, **tags: Any
    ) -> _SpanHandle:
        """Open a child of an EXPLICIT (trace_id, parent_span_id) context —
        the receive side of wire propagation (cluster transport handlers,
        batcher scheduler threads)."""
        trace_id, parent_id = ctx
        span = Span(
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_id=parent_id,
            name=name,
            # staticcheck: ignore[wallclock-duration] user-facing span start epoch timestamp; durations come from start_mono
            start_ms=time.time() * 1e3,
            start_mono=time.monotonic(),
            tags=dict(tags),
        )
        with self._lock:
            # A trace that already sealed (root closed while an async
            # straggler reports) still accepts the span into the sealed
            # list so nothing is silently dropped.
            sealed = self._index.get(trace_id)
            if trace_id in self._active:
                self._active[trace_id][span.span_id] = span
            elif sealed is not None:
                sealed.append(span)
            else:
                self._active.setdefault(trace_id, {})[
                    span.span_id
                ] = span
                self._roots.setdefault(trace_id, span.span_id)
        return _SpanHandle(self, span, task=task)

    def record(
        self,
        ctx: tuple[str, str] | None,
        name: str,
        start_mono: float,
        end_mono: float,
        status: str = "ok",
        **tags: Any,
    ) -> None:
        """Record a RETROSPECTIVE span (already-elapsed interval) under an
        explicit context — the micro-batcher's queue-wait and coalesced-
        launch spans, measured on the scheduler thread after the fact."""
        if ctx is None:
            return
        handle = self.span_from(ctx, name, **tags)
        if handle.span is None:
            return
        handle.span.start_mono = start_mono
        # staticcheck: ignore[wallclock-duration] reconstructs the span's epoch start for the trace export; elapsed part stays monotonic
        handle.span.start_ms = time.time() * 1e3 - max(
            0.0, (time.monotonic() - start_mono) * 1e3
        )
        handle.span.status = status
        handle.span.finish(end_mono)
        self._on_span_closed(handle.span)

    def event(self, name: str, **attrs: Any) -> None:
        """Attach an event to the context's active span (e.g. the planner's
        backend decision). No-op when untraced."""
        ctx = _CURRENT.get()
        if ctx is None:
            return
        with self._lock:
            spans = self._active.get(ctx[0])
            span = None if spans is None else spans.get(ctx[1])
        if span is not None:
            span.add_event(name, **attrs)

    def tag(self, **tags: Any) -> None:
        """Merge tags into the context's active span. No-op untraced."""
        ctx = _CURRENT.get()
        if ctx is None:
            return
        with self._lock:
            spans = self._active.get(ctx[0])
            span = None if spans is None else spans.get(ctx[1])
        if span is not None:
            span.tags.update(tags)

    # ------------------------------------------------------------- sealing

    def _on_span_closed(self, span: Span) -> None:
        # Lock-free fast path: only the trace's ROOT span seals anything
        # (dict reads are GIL-atomic; the root close re-checks under the
        # lock before mutating).
        if self._roots.get(span.trace_id) != span.span_id:
            return
        with self._lock:
            root_id = self._roots.get(span.trace_id)
            if root_id != span.span_id:
                return
            spans = self._active.pop(span.trace_id, None)
            self._roots.pop(span.trace_id, None)
            if spans is None:
                return
            trace = list(spans.values())
            if len(self._ring) == self._ring.maxlen:
                # Capture the entry the full deque is about to evict and
                # drop its index in O(1) — scanning the ring per seal was
                # measured at ~15us/search once the buffer filled.
                evicted_tid, evicted = self._ring[0]
                if self._index.get(evicted_tid) is evicted:
                    self._index.pop(evicted_tid, None)
            self._ring.append((span.trace_id, trace))
            self._index[span.trace_id] = trace

    # -------------------------------------------------------------- export

    def traces(self, limit: int = 50) -> list[dict[str, Any]]:
        """Newest-first summaries of the buffered traces."""
        with self._lock:
            items = list(self._ring)[-limit:]
        out = []
        for trace_id, spans in reversed(items):
            root = next((s for s in spans if s.parent_id is None), spans[0])
            out.append(
                {
                    "trace_id": trace_id,
                    "root": root.name,
                    "status": (
                        "error"
                        if any(s.status == "error" for s in spans)
                        else "ok"
                    ),
                    "spans": len(spans),
                    "start_time_in_millis": int(root.start_ms),
                    "duration_ms": (
                        round(root.duration_ms, 3)
                        if root.duration_ms is not None
                        else None
                    ),
                }
            )
        return out

    def get(self, trace_id: str) -> list[Span] | None:
        """Spans of one trace: sealed first, else the live in-flight set
        (so `profile: true` can inline the request's own tree mid-flight)."""
        with self._lock:
            sealed = self._index.get(trace_id)
            if sealed is not None:
                return list(sealed)
            live = self._active.get(trace_id)
            return None if live is None else list(live.values())

    def export(self, trace_id: str) -> dict[str, Any] | None:
        spans = self.get(trace_id)
        if spans is None:
            return None
        return {
            "trace_id": trace_id,
            "spans": [s.to_json() for s in spans],
        }

    def to_chrome(self, trace_id: str) -> dict[str, Any] | None:
        """Chrome trace-event JSON (the `?format=chrome` shape): complete
        'X' events in microseconds, loadable in Perfetto."""
        spans = self.get(trace_id)
        if spans is None:
            return None
        return chrome_trace([s.to_json() for s in spans])

    def clear(self) -> None:
        """Drop buffered AND in-flight spans (test isolation)."""
        with self._lock:
            self._ring.clear()
            self._index.clear()
            self._active.clear()
            self._roots.clear()

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "buffered_traces": len(self._ring),
                "in_flight_traces": len(self._active),
                "buffer_capacity": self.max_traces,
            }


def splice_spans(span_lists: list[list[dict]]) -> list[dict]:
    """Splice span-JSON fragments collected from several processes into
    ONE tree's span list — the assembly half of distributed tracing.
    Remote spans already parent into the caller's tree via the `_trace`
    wire context, so no id fixup is needed; splicing is dedup (by
    span_id — the same span can arrive both locally and via a fragment
    when cluster members share a process, and a finished version beats an
    in-progress one) plus a stable start-time ordering."""
    by_id: dict[str, dict] = {}
    for spans in span_lists:
        for span in spans or ():
            sid = str(span.get("span_id"))
            prev = by_id.get(sid)
            if prev is None or (
                prev.get("in_progress") and not span.get("in_progress")
            ):
                by_id[sid] = span
    return sorted(
        by_id.values(),
        key=lambda s: (
            s.get("start_ms", s.get("start_time_in_millis", 0)),
            str(s.get("span_id")),
        ),
    )


def collect_fragments(
    local_spans: list[Span] | None, fragment_results: dict
) -> tuple[list[dict], int]:
    """The coordinator half of trace assembly, shared by Node.get_trace
    and ProcCluster.trace: this process' own spans plus the
    `trace_fragment` fan results → (ONE spliced span-JSON list, count of
    remote spans collected)."""
    fragments: list[list[dict]] = []
    if local_spans is not None:
        fragments.append([s.to_json() for s in local_spans])
    collected = 0
    for node_id in sorted(fragment_results):
        spans = (fragment_results[node_id] or {}).get("spans")
        if spans:
            fragments.append(spans)
            collected += len(spans)
    return splice_spans(fragments), collected


def chrome_trace(spans: list[dict]) -> dict[str, Any]:
    """Chrome trace-event JSON from span JSON (`Span.to_json` shapes):
    complete 'X' events in microseconds. Spans are laned by their `node`
    tag — one tid per node — so a spliced cluster trace renders each
    worker process as its own track in Perfetto."""
    tids: dict[str, int] = {}
    events = []
    for span in spans:
        node = str((span.get("tags") or {}).get("node", ""))
        tid = tids.setdefault(node, len(tids) + 1)
        args: dict[str, Any] = {
            "span_id": span.get("span_id"),
            "parent_id": span.get("parent_id"),
            "status": span.get("status", "ok"),
        }
        args.update(span.get("tags") or {})
        if span.get("events"):
            args["events"] = span["events"]
        events.append(
            {
                "name": span.get("name", "?"),
                "ph": "X",
                # Chrome wants microseconds; the float start_ms keeps
                # sub-millisecond ordering of fanned spans.
                "ts": float(
                    span.get(
                        "start_ms", span.get("start_time_in_millis", 0)
                    )
                )
                * 1e3,
                "dur": max(1.0, float(span.get("duration_ms") or 0.0) * 1e3),
                "pid": 1,
                "tid": tid,
                "cat": "estpu",
                "args": args,
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# The process-wide tracer every instrumented site writes through, like
# faults.REGISTRY: in-process cluster nodes share one trace store, which
# is exactly what lets a remote shard execution land in its caller's tree.
TRACER = Tracer()
