"""Observability substrate: distributed tracing + unified metrics.

Two pillars, both process-wide services the serving stack writes through:

- ``tracing``: a thread-safe span tree per request (trace_id/span_id/
  parent), propagated via `traceparent`/`X-Opaque-Id` headers at the REST
  edge and via transport payloads across cluster nodes, buffered in a
  bounded ring exposed at `GET /_traces` (`?format=chrome` emits Chrome
  trace-event JSON loadable in Perfetto).
- ``metrics``: a central registry of counters, gauges and fixed-bucket
  histograms — the single write path behind `_nodes/stats` and the
  Prometheus text exposition at `GET /_metrics`.
"""

from .device import HbmLedger, ProfilerCapture
from .health import INDICATORS, HealthContext, HealthService
from .insights import QueryInsights
from .metrics import DeviceInstruments, MetricsRegistry
from .tracing import TRACER, Span, Tracer

__all__ = [
    "TRACER",
    "Span",
    "Tracer",
    "MetricsRegistry",
    "DeviceInstruments",
    "HbmLedger",
    "ProfilerCapture",
    "HealthService",
    "HealthContext",
    "INDICATORS",
    "QueryInsights",
]
