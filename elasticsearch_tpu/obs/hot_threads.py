"""Per-process hot-threads sampling (`GET /_nodes/hot_threads`).

The reference's monitor/jvm/HotThreads.java samples every JVM thread's
stack N times over an interval, buckets identical stacks, and renders the
busiest threads as text. The Python form: ``sys._current_frames()`` gives
every live thread's current frame; sampling it ``snapshots`` times over
``interval_s`` yields, per thread, (a) how many snapshots caught it OFF a
known-idle wait — the busyness rank; CPython exposes no portable
per-thread CPU clock, so busy-snapshot fraction is the honest stand-in
for the reference's per-thread cpu time — and (b) its most common stack,
rendered reference-style ("M/N snapshots sharing following K elements").

One call samples ONE process. The cluster view fans the ``hot_threads``
wire action over every member and concatenates the per-node texts under
``::: {node}`` headers, so a multi-process topology (cluster/procs.py)
reports each worker's real interpreter state — the pid in the header is
what distinguishes true worker processes from in-process cluster members
sharing the coordinator's interpreter.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter
from typing import Any

# A thread whose TOP frame is one of these functions is parked, not hot:
# waiting on a lock/queue/socket/selector. The analog of the reference's
# known-idle filter (epollWait, Unsafe.park, ...).
_IDLE_TOP_FUNCS = frozenset(
    {
        "wait",
        "_wait_for_tstate_lock",
        "select",
        "poll",
        "epoll",
        "accept",
        "recv",
        "recvfrom",
        "recv_into",
        "readinto",
        "get",
        "sleep",
        "_recv_exact",
        "park",
    }
)
MAX_STACK_DEPTH = 40
MAX_SNAPSHOTS = 100


def _stack_of(frame: Any) -> tuple[str, ...]:
    out: list[str] = []
    while frame is not None and len(out) < MAX_STACK_DEPTH:
        code = frame.f_code
        out.append(
            f"{code.co_name} "
            f"({os.path.basename(code.co_filename)}:{frame.f_lineno})"
        )
        frame = frame.f_back
    return tuple(out)


def sample_hot_threads(
    threads: int = 3,
    interval_s: float = 0.5,
    snapshots: int = 10,
    metrics=None,
) -> list[dict[str, Any]]:
    """Sample this process' threads; busiest first.

    Each entry: thread name, busy-snapshot count, total snapshots, the
    most common stack (top frame first) and how many snapshots shared it.
    The sampling thread itself is excluded — a hot-threads request must
    never report its own collection loop as the hottest thread."""
    snapshots = max(1, min(MAX_SNAPSHOTS, int(snapshots)))
    threads = max(1, int(threads))
    interval_s = max(0.0, min(30.0, float(interval_s)))
    pause = interval_s / snapshots
    me = threading.get_ident()
    busy: Counter = Counter()
    seen: Counter = Counter()
    stacks: dict[int, Counter] = {}
    for i in range(snapshots):
        if i and pause:
            time.sleep(pause)
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = _stack_of(frame)
            if not stack:
                continue
            seen[ident] += 1
            top_func = stack[0].split(" (", 1)[0]
            if top_func not in _IDLE_TOP_FUNCS:
                busy[ident] += 1
            stacks.setdefault(ident, Counter())[stack] += 1
    if metrics is not None:
        metrics.counter(
            "estpu_hot_threads_samples_total",
            "Hot-threads stack snapshots taken by this process",
        ).inc(snapshots)
    names = {t.ident: t.name for t in threading.enumerate()}
    ranked = sorted(
        seen, key=lambda i: (-busy[i], -seen[i], names.get(i, ""))
    )
    out = []
    for ident in ranked[:threads]:
        stack, shared = stacks[ident].most_common(1)[0]
        out.append(
            {
                "name": names.get(ident, f"thread-{ident}"),
                "busy_snapshots": int(busy[ident]),
                "snapshots": snapshots,
                "stack": list(stack),
                "stack_shared_by": int(shared),
            }
        )
    return out


def fan_text_blocks(
    results: dict, failures: list[dict], order=None
) -> list[str]:
    """Per-node text blocks of a `hot_threads` fan, shared by the Node
    and ProcCluster assemblers: sampled nodes in the given order, then
    one failure line per node that could not be sampled."""
    blocks = [
        str((results[node_id] or {}).get("text", ""))
        for node_id in (sorted(results) if order is None else order)
        if node_id in results
    ]
    for failure in failures:
        blocks.append(
            f"::: {{{failure['node']}}}\n   hot_threads collection "
            f"failed: {failure['reason']}\n"
        )
    return blocks


def hot_threads_text(
    node_name: str = "",
    threads: int = 3,
    interval_s: float = 0.5,
    snapshots: int = 10,
    metrics=None,
) -> str:
    """The reference-style text block for one process' sample."""
    sampled = sample_hot_threads(
        threads=threads,
        interval_s=interval_s,
        snapshots=snapshots,
        metrics=metrics,
    )
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime())
    lines = [
        f"::: {{{node_name or 'node'}}} pid[{os.getpid()}]",
        f"   Hot threads at {stamp}Z, interval={int(interval_s * 1e3)}ms, "
        f"busiestThreads={threads}, snapshots={snapshots}:",
        "",
    ]
    for entry in sampled:
        lines.append(
            f"   {entry['busy_snapshots']}/{entry['snapshots']} snapshots "
            f"busy in thread '{entry['name']}'"
        )
        lines.append(
            f"     {entry['stack_shared_by']}/{entry['snapshots']} "
            f"snapshots sharing following {len(entry['stack'])} elements"
        )
        for element in entry["stack"]:
            lines.append(f"       {element}")
        lines.append("")
    return "\n".join(lines)
