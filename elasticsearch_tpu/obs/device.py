"""Device observability: the HBM ledger, the XLA compile census, and the
on-demand profiler capture — the single source of truth for device-side
telemetry (ISSUE 14).

Three pieces, all feeding `_nodes/stats` and `GET /_metrics`:

- **HbmLedger** — every byte that lands on device registers here under
  (label, scope): packed segments (index/tiles.py uploads, charged by the
  engine), filter-cache mask planes, ANN IVF tiles, packed multi-tenant
  planes, and SPMD mesh snapshots. The node HBM circuit breaker
  (common/breaker.py) WRITES THROUGH to the ledger on every
  add/add_unchecked/release, so breaker accounting and ledger accounting
  cannot drift — the consistency law (tests/test_device_obs.py): ledger
  totals equal the sum of each component's own byte stats through
  refresh / evict / `_cache/clear` / delete_index cycles, drift zero.
  Surfaced as `estpu_hbm_bytes{label,index}` gauges + a high-watermark
  gauge, the `device.hbm` section of `_nodes/stats` (fanned per node via
  the PR-13 scatter), and `GET /_cat/hbm`.

- **Compile census** — a process-wide `jax.monitoring` listener counts
  REAL backend compiles (`/jax/core/compile/backend_compile_duration`),
  attributed to the plan class of the launch in flight on the compiling
  thread (DeviceInstruments.timed sets the attribution window). A compile
  that fires during a launch whose plan key was ALREADY seen is a
  **retrace** (`estpu_device_retraces_total{plan_class}`): the plan key
  failed to capture a varying shape — the alarm that catches accidental
  shape-polymorphism regressions (a recompile-per-query silently triples
  p50 long before anyone reads a profile).

- **ProfilerCapture** — `POST /_profiler/start` / `POST /_profiler/stop`
  drive `jax.profiler.start_trace`/`stop_trace` (single-flight, bounded
  duration, 409 on double-start), return the Perfetto-loadable trace
  directory, and stamp the capture window into the obs trace ring
  (`profiler.capture` trace) so device traces and the PR-4/13 request
  traces can be laid side by side on one clock.

`LEDGER_LABELS` is the machine-checked label registry: staticcheck's
registry-breaker-label rule fails the gate on any `CircuitBreaker.add`
(or release) whose literal label is not declared here — a breaker label
allocated outside the ledger would silently split the two accountings.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

# Canonical HBM ledger labels. Every breaker/ledger byte carries one of
# these (f-string labels match by prefix, like fault-site patterns);
# staticcheck registry-breaker-label enforces the registry at every
# breaker call site.
LEDGER_LABELS = (
    "segment",  # packed engine segments (index/tiles.pack_segment)
    "filter_cache",  # device-resident filter mask planes
    "ann_cache",  # IVF partition tiles (index/ann.py)
    "packed_plane",  # multi-tenant packed planes (exec/packed.py)
    "mesh_plane",  # SPMD mesh snapshot buffers (parallel/mesh_serving)
)

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# ---------------------------------------------------------------------------
# Process-wide accounting (bench.py reads these across every Node the
# configs construct): total resident ledger bytes, lifetime high
# watermark, a resettable measurement-window peak, and the compile
# census. One lock — these are tiny counter updates.
# ---------------------------------------------------------------------------

_PROC_LOCK = threading.Lock()
_PROC = {"total": 0, "hwm": 0, "window_floor": 0, "window_peak": 0}
_CENSUS = {"compiles": 0, "compile_s": 0.0, "retraces": 0}
_LISTENER_REGISTERED = False
# Thread-local attribution window: (DeviceInstruments, plan_class,
# retraceable) while a wrapped launch is dispatching on this thread.
_TLS = threading.local()


def _proc_register(nbytes: int) -> None:
    with _PROC_LOCK:
        _PROC["total"] += nbytes
        if _PROC["total"] > _PROC["hwm"]:
            _PROC["hwm"] = _PROC["total"]
        if _PROC["total"] > _PROC["window_peak"]:
            _PROC["window_peak"] = _PROC["total"]


def _proc_release(nbytes: int) -> None:
    with _PROC_LOCK:
        _PROC["total"] = max(0, _PROC["total"] - nbytes)


def begin_hbm_window() -> None:
    """Start a process-wide HBM measurement window (bench.py brackets
    each config with one so `hbm_high_watermark_bytes` is the CONFIG's
    incremental peak, not whatever an earlier config left resident)."""
    with _PROC_LOCK:
        _PROC["window_floor"] = _PROC["total"]
        _PROC["window_peak"] = _PROC["total"]


def hbm_window_peak() -> int:
    """Peak ledger bytes ABOVE the window floor since begin_hbm_window."""
    with _PROC_LOCK:
        return max(0, _PROC["window_peak"] - _PROC["window_floor"])


def process_census() -> dict[str, Any]:
    """Process-wide compile census snapshot: real XLA backend compiles
    (jax.monitoring), wall seconds spent compiling, and retraces (a
    compile during a launch whose plan key was already seen)."""
    with _PROC_LOCK:
        return {
            "compiles": _CENSUS["compiles"],
            "compile_s": round(_CENSUS["compile_s"], 3),
            "retraces": _CENSUS["retraces"],
        }


def note_retraces(n: int) -> None:
    """Fold retraces detected by a DeviceInstruments timed window into
    the process census (bench.py's per-config gate reads deltas here)."""
    with _PROC_LOCK:
        _CENSUS["retraces"] += int(n)


def _on_compile_event(key: str, duration_s: float, **_kw: Any) -> None:
    if key != _COMPILE_EVENT:
        return
    with _PROC_LOCK:
        _CENSUS["compiles"] += 1
        _CENSUS["compile_s"] += duration_s
    window = getattr(_TLS, "launch_window", None)
    if window is not None:
        window.note_compile(duration_s)


def ensure_compile_listener() -> None:
    """Register the process-wide compile-event listener once. jax offers
    no unregister, so this is a lifetime hook — it only bumps counters."""
    global _LISTENER_REGISTERED
    with _PROC_LOCK:
        if _LISTENER_REGISTERED:
            return
        _LISTENER_REGISTERED = True
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_on_compile_event)


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------


class HbmLedger:
    """Byte accounting for everything resident on device, by (label,
    scope). Scopes are the components' own cache-scope tokens (engine
    uid, mesh scope tuple, "_packed"); `name_scope` maps them to index
    names for the {label,index} gauge rendering. The breaker writes
    through (`breaker_backed=True`), so `breaker_drift_bytes` is
    structurally zero; components the breaker does not guard (packed
    planes, mesh snapshots) register directly."""

    def __init__(self, metrics=None, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._bytes: dict[tuple[str, Any], int] = {}
        self._names: dict[Any, str] = {}
        self._total = 0
        self._hwm = 0
        self._breaker_bytes = 0
        self.breaker = None  # set by CircuitBreaker(ledger=...)
        self.metrics = metrics
        self._gauged: set[tuple[str, str]] = set()
        if metrics is not None:
            metrics.gauge(
                "estpu_hbm_high_watermark_bytes",
                "Lifetime peak of total ledger-resident device bytes",
                fn=lambda: self._hwm,
            )

    # ------------------------------------------------------------- naming

    def name_scope(self, scope: Any, index_name: str) -> None:
        """Associate a component scope token with an index name (the
        gauge/cat `index` column). Idempotent; unknown scopes render as
        `_node`. Bytes may register BEFORE naming (boot recovery packs
        segments while the engine is constructed, before the node can
        name its uid) — re-ensure the named gauge series for any label
        already holding bytes under this scope, so the recovered HBM is
        visible at `/_metrics` immediately (the old `_node` series reads
        0 from then on)."""
        with self._lock:
            self._names[scope] = index_name
            labels = {
                label for (label, s) in self._bytes if s == scope
            }
        for label in labels:
            self._ensure_gauge(label, index_name)

    def forget_scope(self, scope: Any) -> None:
        with self._lock:
            self._names.pop(scope, None)

    def _index_of(self, scope: Any) -> str:
        name = self._names.get(scope)
        if name is not None:
            return name
        return "_node"

    # --------------------------------------------------------- accounting

    def register(
        self,
        label: str,
        scope: Any,
        nbytes: int,
        breaker_backed: bool = False,
    ) -> None:
        """Account `nbytes` landing on device under (label, scope)."""
        if not self.enabled or nbytes <= 0:
            return
        nbytes = int(nbytes)
        base = _base_label(label)
        key = (base, scope)
        with self._lock:
            self._bytes[key] = self._bytes.get(key, 0) + nbytes
            self._total += nbytes
            if self._total > self._hwm:
                self._hwm = self._total
            if breaker_backed:
                self._breaker_bytes += nbytes
            index = self._index_of(scope)
        _proc_register(nbytes)
        self._ensure_gauge(base, index)

    def release(
        self,
        label: str,
        scope: Any,
        nbytes: int,
        breaker_backed: bool = False,
    ) -> None:
        """Account `nbytes` leaving the device. Clamped per key: the
        ledger can never go negative, mirroring the breaker's own clamp."""
        if not self.enabled or nbytes <= 0:
            return
        nbytes = int(nbytes)
        key = (_base_label(label), scope)
        with self._lock:
            held = self._bytes.get(key, 0)
            taken = min(held, nbytes)
            if taken:
                remaining = held - taken
                if remaining:
                    self._bytes[key] = remaining
                else:
                    del self._bytes[key]
                self._total -= taken
            if breaker_backed:
                self._breaker_bytes = max(0, self._breaker_bytes - nbytes)
        _proc_release(nbytes)

    def _ensure_gauge(self, label: str, index: str) -> None:
        if self.metrics is None:
            return
        with self._lock:
            if (label, index) in self._gauged:
                return
            self._gauged.add((label, index))
        self.metrics.gauge(
            "estpu_hbm_bytes",
            "Device bytes resident per ledger label and index",
            fn=lambda l=label, i=index: self._label_index_bytes(l, i),
            label=label,
            index=index,
        )

    def _label_index_bytes(self, label: str, index: str) -> int:
        with self._lock:
            return sum(
                n
                for (lbl, scope), n in self._bytes.items()
                if lbl == label and self._index_of(scope) == index
            )

    # -------------------------------------------------------------- views

    def bytes_for(self, label: str, scope: Any = None) -> int:
        """Resident bytes of one label (optionally one scope) — the
        consistency-law accessor the tests gate on."""
        base = _base_label(label)
        with self._lock:
            if scope is not None:
                return self._bytes.get((base, scope), 0)
            return sum(
                n for (lbl, _s), n in self._bytes.items() if lbl == base
            )

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    @property
    def high_watermark_bytes(self) -> int:
        with self._lock:
            return self._hwm

    def snapshot(self) -> dict[str, Any]:
        """The `device.hbm` section of `_nodes/stats`."""
        if not self.enabled:
            return self.disabled_snapshot()
        with self._lock:
            by_label: dict[str, int] = {}
            rows: dict[tuple[str, str], int] = {}
            for (label, scope), n in self._bytes.items():
                by_label[label] = by_label.get(label, 0) + n
                rk = (label, self._index_of(scope))
                rows[rk] = rows.get(rk, 0) + n
            total = self._total
            hwm = self._hwm
            breaker_bytes = self._breaker_bytes
        out: dict[str, Any] = {
            "enabled": True,
            "total_bytes": total,
            "high_watermark_bytes": hwm,
            "by_label": {k: by_label[k] for k in sorted(by_label)},
            "by_label_index": [
                {"label": label, "index": index, "bytes": rows[(label, index)]}
                for label, index in sorted(rows)
            ],
        }
        if self.breaker is not None:
            used = self.breaker.stats()["estimated_size_in_bytes"]
            out["breaker_used_bytes"] = used
            # Structurally zero: every breaker mutation writes through.
            out["breaker_drift_bytes"] = used - breaker_bytes
        return out

    @staticmethod
    def disabled_snapshot() -> dict[str, Any]:
        """Section shape under ESTPU_DEVICE_OBS=0 — present, inert."""
        return {
            "enabled": False,
            "total_bytes": 0,
            "high_watermark_bytes": 0,
            "by_label": {},
            "by_label_index": [],
        }

    @staticmethod
    def computed_section(
        engines=(),
        filter_cache=None,
        ann_cache=None,
        engines_by_index: dict[str, list] | None = None,
    ) -> dict[str, Any]:
        """A ledger-shaped `device.hbm` section computed from component
        stats — the per-ClusterNode form (workers carry no breaker, so
        no write-through ledger; by the consistency law the computed
        totals ARE the ledger totals). A computed section carries NO
        high watermark — the instantaneous total is not a peak, and a
        fake one would silently mean something different from the
        coordinating node's real lifetime peak. `engines_by_index`
        (index name -> engines) attributes segment rows per index; the
        flat `engines` form lands under `_node`."""
        by_label: dict[str, int] = {}
        rows: list[dict[str, Any]] = []
        if engines_by_index:
            seg = 0
            for index in sorted(engines_by_index):
                n = int(
                    sum(e.device_bytes for e in engines_by_index[index])
                )
                if n:
                    rows.append(
                        {"label": "segment", "index": index, "bytes": n}
                    )
                seg += n
        else:
            seg = int(sum(e.device_bytes for e in engines))
            if seg:
                rows.append(
                    {"label": "segment", "index": "_node", "bytes": seg}
                )
        if seg:
            by_label["segment"] = seg
        if filter_cache is not None:
            fc = int(filter_cache.stats()["bytes_resident"])
            if fc:
                by_label["filter_cache"] = fc
                rows.append(
                    {"label": "filter_cache", "index": "_node", "bytes": fc}
                )
        if ann_cache is not None:
            ann = int(ann_cache.stats()["bytes_resident"])
            if ann:
                by_label["ann_cache"] = ann
                rows.append(
                    {"label": "ann_cache", "index": "_node", "bytes": ann}
                )
        return {
            "enabled": True,
            "source": "computed",
            "total_bytes": sum(by_label.values()),
            "by_label": by_label,
            "by_label_index": sorted(
                rows, key=lambda r: (r["label"], r["index"])
            ),
        }


def _base_label(label: str) -> str:
    """Canonical ledger label of a (possibly decorated) breaker label:
    the longest LEDGER_LABELS entry the label starts with, so dynamic
    suffixes collapse onto one bounded-cardinality series."""
    for known in LEDGER_LABELS:
        if label == known or label.startswith(known):
            return known
    return label


# ---------------------------------------------------------------------------
# Profiler capture
# ---------------------------------------------------------------------------


class ProfilerConflictError(Exception):
    """A capture is already running (HTTP 409)."""


class ProfilerInactiveError(Exception):
    """No capture is running (HTTP 400)."""


class ProfilerCapture:
    """Single-flight `jax.profiler` capture with a bounded duration.

    `start()` opens `jax.profiler.start_trace(trace_dir)`; a watchdog
    timer force-stops the capture at `duration_s` (clamped to
    ESTPU_PROFILER_MAX_S, default 120) so a forgotten capture can never
    grow a trace directory unbounded. `stop()` closes the capture,
    returns the Perfetto trace directory, and stamps the capture window
    into the obs trace ring as a `profiler.capture` trace whose span
    covers [start, stop] on the same clock as every request trace."""

    def __init__(self, base_dir: str | None = None):
        self._lock = threading.Lock()
        self._active: dict[str, Any] | None = None
        self._timer: threading.Timer | None = None
        self._captures = 0
        self.base_dir = base_dir

    @staticmethod
    def _max_duration_s() -> float:
        return float(os.environ.get("ESTPU_PROFILER_MAX_S", 120.0))

    def start(
        self, duration_s: float | None = None, trace_dir: str | None = None
    ) -> dict[str, Any]:
        import tempfile

        import jax

        bound = self._max_duration_s()
        if duration_s is None:
            duration_s = bound
        duration_s = min(float(duration_s), bound)
        if duration_s <= 0:
            raise ValueError(
                f"profiler duration must be positive, got {duration_s}"
            )
        with self._lock:
            if self._active is not None:
                raise ProfilerConflictError(
                    "a profiler capture is already running "
                    f"(trace_dir [{self._active['trace_dir']}]); stop it "
                    "before starting another"
                )
            if trace_dir is None:
                trace_dir = tempfile.mkdtemp(
                    prefix="estpu_profile_", dir=self.base_dir
                )
            jax.profiler.start_trace(trace_dir)
            self._captures += 1
            self._active = {
                "trace_dir": trace_dir,
                # staticcheck: ignore[wallclock-duration] user-facing capture start epoch timestamp; durations come from the monotonic twin
                "started_at_ms": time.time() * 1e3,
                "started_mono": time.monotonic(),
                "bound_s": duration_s,
            }
            timer = threading.Timer(duration_s, self._expire)
            timer.daemon = True
            timer.start()
            self._timer = timer
            return {
                "acknowledged": True,
                "trace_dir": trace_dir,
                "max_duration_s": duration_s,
            }

    def _expire(self) -> None:
        """Watchdog: force-stop a capture that outlived its bound."""
        try:
            self.stop(reason="expired")
        except ProfilerInactiveError:
            pass  # raced a user stop; nothing to do

    def stop(self, reason: str = "requested") -> dict[str, Any]:
        import jax

        with self._lock:
            active = self._active
            if active is None:
                raise ProfilerInactiveError("no profiler capture is running")
            self._active = None
            timer, self._timer = self._timer, None
            jax.profiler.stop_trace()
        if timer is not None:
            timer.cancel()
        duration_ms = (time.monotonic() - active["started_mono"]) * 1e3
        # Stamp the capture window into the obs trace ring: one
        # `profiler.capture` trace whose root span covers the window, so
        # `GET /_traces` lays the device capture alongside request traces.
        from .tracing import TRACER

        handle = TRACER.start_trace(
            "profiler.capture",
            trace_dir=active["trace_dir"],
            reason=reason,
        )
        if handle.span is not None:
            handle.span.start_ms = active["started_at_ms"]
            handle.span.start_mono = active["started_mono"]
        with handle:
            pass  # enter+exit: finish() seals the window into the ring
        return {
            "acknowledged": True,
            "trace_dir": active["trace_dir"],
            "duration_ms": round(duration_ms, 3),
            "stopped": reason,
            "trace_id": (
                handle.span.trace_id if handle.span is not None else None
            ),
        }

    def status(self) -> dict[str, Any]:
        with self._lock:
            active = self._active
            captures = self._captures
        if active is None:
            return {"running": False, "captures_total": captures}
        return {
            "running": True,
            "captures_total": captures,
            "trace_dir": active["trace_dir"],
            "elapsed_ms": round(
                (time.monotonic() - active["started_mono"]) * 1e3, 3
            ),
            "max_duration_s": active["bound_s"],
        }
