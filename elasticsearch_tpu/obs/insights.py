"""Query insights: a bounded top-N slowest-searches sample.

The structured analog of the search slowlog (ISSUE 15): where the
slowlog emits text lines past a configured threshold, the insights ring
keeps the N slowest searches seen so far as STRUCTURED entries — took,
index, the per-phase breakdown and chosen backend(s) from the same
`SearchResponse.phases` hook the slowlog reads, the response's shard
math, and the request's trace_id as an exemplar (join against
`GET /_traces/{id}` for the full span tree). Served at
`GET /_insights/queries`.

Admission is a min-heap on took: a search enters only while the ring has
room or it is slower than the current fastest member, so the ring
converges on the true top-N without unbounded memory — and a storm of
fast queries can never wash the slow exemplars out.
"""

from __future__ import annotations

import heapq
import json
import threading
import time
from typing import Any


class QueryInsights:
    """Thread-safe bounded top-N slowest-searches sample."""

    def __init__(self, capacity: int = 100, metrics=None):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        # Min-heap of (took_ms, seq, entry): the root is the FASTEST
        # retained search — the admission bar.
        self._heap: list[tuple[float, int, dict]] = []
        self._seq = 0
        self.metrics = metrics
        if metrics is not None:
            self._recorded = metrics.counter(
                "estpu_insights_recorded_total",
                "Searches offered to the insights ring (recorded + "
                "rejected-by-bar)",
            )
            metrics.gauge(
                "estpu_insights_entries",
                "Entries resident in the insights top-N ring",
                fn=lambda: len(self._heap),
            )
        else:
            self._recorded = None

    def record(
        self,
        index: str,
        took_ms: int,
        shards: dict | None = None,
        trace_id: str | None = None,
        phases: dict | None = None,
        source: dict | None = None,
        tenant: str | None = None,
    ) -> None:
        if self._recorded is not None:
            self._recorded.inc()
        with self._lock:
            if (
                len(self._heap) >= self.capacity
                and took_ms <= self._heap[0][0]
            ):
                return  # faster than every retained entry: not insight
            entry: dict[str, Any] = {
                "took_ms": int(took_ms),
                "index": index,
                # staticcheck: ignore[wallclock-duration] user-facing epoch stamp on the entry; nothing measures durations from it
                "timestamp_ms": int(time.time() * 1e3),
            }
            if trace_id:
                entry["trace_id"] = trace_id
            if tenant is not None:
                # QoS lane attribution: exemplars answer "WHOSE slow
                # query" without a second lookup.
                entry["tenant"] = tenant
            if shards:
                entry["shards"] = {
                    k: shards[k]
                    for k in ("total", "successful", "skipped", "failed")
                    if k in shards
                }
            if phases:
                entry["phases"] = {
                    k: v for k, v in phases.items() if k != "backends"
                }
                if phases.get("backends"):
                    # Planner-chosen execution backend(s) (per-segment
                    # tally) — the plan-class attribution the slowlog
                    # never carried.
                    entry["backends"] = dict(phases["backends"])
            if source is not None:
                entry["source"] = json.dumps(
                    source, separators=(",", ":")
                )[:1000]
            self._seq += 1
            item = (float(took_ms), self._seq, entry)
            if len(self._heap) >= self.capacity:
                heapq.heapreplace(self._heap, item)
            else:
                heapq.heappush(self._heap, item)

    def queries(self, size: int | None = None) -> list[dict]:
        """Retained entries, slowest first."""
        with self._lock:
            items = sorted(self._heap, key=lambda t: (-t[0], -t[1]))
        out = [dict(entry) for _took, _seq, entry in items]
        if size is not None:
            out = out[: max(0, int(size))]
        return out

    def clear(self) -> int:
        with self._lock:
            n = len(self._heap)
            self._heap = []
        return n

    def stats(self) -> dict[str, Any]:
        with self._lock:
            entries = len(self._heap)
            bar = self._heap[0][0] if self._heap else 0.0
        return {
            "entries": entries,
            "capacity": self.capacity,
            "min_retained_took_ms": int(bar),
            "recorded_total": (
                int(self._recorded.value)
                if self._recorded is not None
                else 0
            ),
        }
