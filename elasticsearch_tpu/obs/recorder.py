"""Flight recorder: an always-on bounded ring of periodic lightweight
cluster snapshots (ISSUE 19).

Every windowed instrument ages out in 60s and the trace ring churns, so
by the time anyone looks at an incident the evidence is gone. The
recorder keeps the last N *frames* — per-indicator health statuses,
rolling-window deltas (`estpu_*_recent` p50/p99/rates), breaker/HBM
ledger totals, QoS lane summaries, top insights exemplar trace_ids —
recorded on the health poll's cadence, so an incident capsule
(obs/incidents.py) can always splice in what the cluster looked like
*before* the trigger, not just after.

A frame is a plain dict snapshot of already-computed numbers: recording
one costs dict assembly, never a fan, never a device call — the ring is
safe to feed at 1/s forever (the bench cfg17 gate).
"""

from __future__ import annotations

import threading
import time
from typing import Any

DEFAULT_CAPACITY = 240  # 4 minutes of 1/s polls


class FlightRecorder:
    """Bounded ring of timestamped frames, newest last.

    `record` stamps and appends; `frames` filters by wall-clock window
    (the incident capsule's pre/post splice); both are lock-cheap.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        metrics=None,
    ):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._frames: list[dict] = []  # newest last, bounded
        self._seq = 0
        self.metrics = metrics
        if metrics is not None:
            self._frames_c = metrics.counter(
                "estpu_recorder_frames_total",
                "Flight-recorder frames recorded (health-poll cadence)",
            )
            metrics.gauge(
                "estpu_recorder_frames",
                "Flight-recorder frames resident in the bounded ring",
                fn=lambda: len(self._frames),
            )
        else:
            self._frames_c = None

    def record(
        self,
        statuses: dict[str, str] | None = None,
        extras: dict[str, Any] | None = None,
    ) -> dict:
        """Append one frame: indicator statuses plus whatever windowed/
        ledger extras the caller snapshotted. Returns the frame."""
        frame: dict[str, Any] = {
            # staticcheck: ignore[wallclock-duration] operator-facing timestamp, not a duration
            "at_ms": int(time.time() * 1e3),
            "statuses": dict(statuses or {}),
        }
        if extras:
            frame.update(extras)
        with self._lock:
            self._seq += 1
            frame["seq"] = self._seq
            self._frames.append(frame)
            if len(self._frames) > self.capacity:
                del self._frames[: -self.capacity]
        if self._frames_c is not None:
            self._frames_c.inc()
        return frame

    def frames(
        self,
        since_ms: int | None = None,
        until_ms: int | None = None,
        limit: int | None = None,
    ) -> list[dict]:
        """Frames inside [since_ms, until_ms] (wall clock), oldest
        first; `limit` keeps the newest N of the selection."""
        with self._lock:
            out = list(self._frames)
        if since_ms is not None:
            out = [f for f in out if f["at_ms"] >= since_ms]
        if until_ms is not None:
            out = [f for f in out if f["at_ms"] <= until_ms]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def last(self) -> dict | None:
        with self._lock:
            return self._frames[-1] if self._frames else None

    def stats(self) -> dict:
        with self._lock:
            return {
                "frames": len(self._frames),
                "capacity": self.capacity,
                "recorded_total": self._seq,
            }
