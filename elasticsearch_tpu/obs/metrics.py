"""Unified metrics registry: counters, gauges, fixed-bucket histograms.

The single write path behind the node's operational counters: the
scattered per-subsystem dicts (exec planner decisions, micro-batcher
telemetry, search-resilience counters, request-cache hit/miss/eviction,
replication gateway retries) write through registry instruments, and
`GET /_nodes/stats` is rebuilt as a VIEW over the registry — one source
of truth, two renderings (the ES-shaped stats JSON and the Prometheus
text exposition at `GET /_metrics`).

Device-level instruments (DeviceInstruments) hook the kernel-launch
sites: XLA compile count and compile-ms per plan class (first launch of a
new (kernel, spec, k) shape is the compile), padding-waste ratio of
coalesced launches (padded nt vs. actual), host→device transfer bytes,
and launch counts — the signals BENCH_r05-style regressions (cfg3_conj at
0.07×, tunnel_roundtrip_floor_ms 106.2) need span-level attribution for.

Prometheus exposition follows the text format 0.0.4: `# TYPE` per family,
`name{label="value"} <float>` samples, histogram `_bucket`/`_sum`/`_count`
series with cumulative `le` buckets.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Callable

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_label(value: Any) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace("\n", r"\n")
        .replace('"', r'\"')
    )


def _format_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotonically increasing value (one labeled sample)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counters only go up, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Settable value, or a callback evaluated at scrape time."""

    __slots__ = ("_value", "_fn", "_lock")

    def __init__(self, fn: Callable[[], float] | None = None):
        self._value = 0.0
        self._fn = fn
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            # staticcheck: ignore[broad-except] a failing gauge callback must not 500 the scrape; the sample reads 0
            except Exception:
                return 0.0
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram: per-bucket (non-cumulative) counts, sum,
    count. Buckets are upper bounds; values above the last bound land in
    the implicit +Inf bucket. The exposition renders the cumulative
    `le`-labeled series Prometheus expects."""

    __slots__ = ("buckets", "_counts", "_inf", "_sum", "_count", "_lock")

    def __init__(self, buckets: tuple[float, ...]):
        if not buckets:
            raise ValueError("histogram requires at least one bucket bound")
        ordered = tuple(sorted(float(b) for b in buckets))
        if len(set(ordered)) != len(ordered):
            raise ValueError(f"duplicate histogram bucket in {buckets}")
        self.buckets = ordered
        self._counts = [0] * len(ordered)
        self._inf = 0
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    self._counts[i] += 1
                    return
            self._inf += 1

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "buckets": {
                    _format_value(b): c
                    for b, c in zip(self.buckets, self._counts)
                },
                "inf": self._inf,
                "sum": self._sum,
                "count": self._count,
            }

class WindowedHistogram:
    """Rolling-window latency sketch: a ring of fixed-interval buckets,
    each holding a bounded sample list, covering the trailing
    ``window_s`` seconds. Every existing instrument is cumulative since
    boot; health indicators need RECENT behavior — "is the queue backing
    up NOW", not "did it ever". ``record`` is lock-cheap (one lock, one
    append); ``snapshot`` computes p50/p99/rate over only the buckets
    still inside the window, so stale load ages out within one bucket
    interval of leaving it."""

    __slots__ = (
        "window_s", "interval_s", "n_buckets", "cap_per_bucket",
        "_lock", "_samples", "_counts", "_epochs",
    )

    def __init__(
        self,
        window_s: float = 60.0,
        interval_s: float = 5.0,
        cap_per_bucket: int = 512,
    ):
        self.window_s = float(window_s)
        self.interval_s = max(0.05, float(interval_s))
        # +1 ring slot: the current (partial) bucket plus a full window
        # of sealed buckets.
        self.n_buckets = max(1, int(round(window_s / self.interval_s))) + 1
        self.cap_per_bucket = max(1, int(cap_per_bucket))
        self._lock = threading.Lock()
        self._samples: list[list[float]] = [
            [] for _ in range(self.n_buckets)
        ]
        # Full count per bucket (the sample list caps; the count doesn't,
        # so rates stay honest under bursts past the cap).
        self._counts = [0] * self.n_buckets
        self._epochs = [-1] * self.n_buckets

    def _slot(self, now: float) -> int:
        """Rotate to the bucket owning `now`; returns its ring index.
        Caller holds the lock."""
        epoch = int(now / self.interval_s)
        idx = epoch % self.n_buckets
        if self._epochs[idx] != epoch:
            self._samples[idx] = []
            self._counts[idx] = 0
            self._epochs[idx] = epoch
        return idx

    def record(self, value: float) -> None:
        now = time.monotonic()
        with self._lock:
            idx = self._slot(now)
            self._counts[idx] += 1
            bucket = self._samples[idx]
            if len(bucket) < self.cap_per_bucket:
                bucket.append(float(value))

    def snapshot(self) -> dict[str, Any]:
        """{count, rate_per_s, p50, p99, mean, max} over the trailing
        window (zeros when the window is empty)."""
        now = time.monotonic()
        floor = int(now / self.interval_s) - (self.n_buckets - 1)
        samples: list[float] = []
        count = 0
        with self._lock:
            for i in range(self.n_buckets):
                if self._epochs[i] >= floor:
                    samples.extend(self._samples[i])
                    count += self._counts[i]
        if not samples:
            return {
                "count": 0, "rate_per_s": 0.0, "p50": 0.0, "p99": 0.0,
                "mean": 0.0, "max": 0.0,
            }
        ordered = sorted(samples)
        n = len(ordered)

        def pct(q: float) -> float:
            return ordered[min(n - 1, int(q * (n - 1) + 0.5))]

        return {
            "count": int(count),
            "rate_per_s": round(count / self.window_s, 4),
            "p50": round(pct(0.50), 4),
            "p99": round(pct(0.99), 4),
            "mean": round(sum(ordered) / n, 4),
            "max": round(ordered[-1], 4),
        }

    def stat(self, name: str) -> float:
        return float(self.snapshot().get(name, 0.0))

    def count(self) -> float:
        """Samples inside the trailing window (WindowedCounter parity)."""
        return float(self.snapshot()["count"])


class WindowedCounter:
    """Rolling-window event counter: ring of per-interval counts; the
    windowed sibling of a cumulative Counter for rate-style health rules
    (shed rate, eviction bursts, transport churn)."""

    __slots__ = ("window_s", "interval_s", "n_buckets", "_lock", "_counts",
                 "_epochs")

    def __init__(self, window_s: float = 60.0, interval_s: float = 5.0):
        self.window_s = float(window_s)
        self.interval_s = max(0.05, float(interval_s))
        self.n_buckets = max(1, int(round(window_s / self.interval_s))) + 1
        self._lock = threading.Lock()
        self._counts = [0.0] * self.n_buckets
        self._epochs = [-1] * self.n_buckets

    def inc(self, n: float = 1.0) -> None:
        now = time.monotonic()
        epoch = int(now / self.interval_s)
        idx = epoch % self.n_buckets
        with self._lock:
            if self._epochs[idx] != epoch:
                self._counts[idx] = 0.0
                self._epochs[idx] = epoch
            self._counts[idx] += n

    def count(self) -> float:
        """Events inside the trailing window."""
        now = time.monotonic()
        floor = int(now / self.interval_s) - (self.n_buckets - 1)
        with self._lock:
            return float(
                sum(
                    c
                    for c, e in zip(self._counts, self._epochs)
                    if e >= floor
                )
            )

    def rate_per_s(self) -> float:
        return round(self.count() / self.window_s, 4)

    def snapshot(self) -> dict[str, Any]:
        count = self.count()
        return {
            "count": int(count),
            "rate_per_s": round(count / self.window_s, 4),
        }

    def stat(self, name: str) -> float:
        return float(self.snapshot().get(name, 0.0))


class MetricsRegistry:
    """Thread-safe instrument registry with Prometheus text exposition.

    Instruments are keyed by (name, sorted label items): repeated
    ``counter(name, **labels)`` calls return the same instrument, so call
    sites don't pre-register anything."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, help, {label_tuple: instrument})
        self._families: dict[str, tuple[str, str, dict]] = {}
        # Rolling-window instruments, keyed (name, label_key). They live
        # OUTSIDE _families (their exposition is the `stat`-labeled gauge
        # series windowed_* registers), so _collect/merge stay unchanged.
        self._windows: dict[tuple, Any] = {}

    # ------------------------------------------------------------ creation

    def _family(self, name: str, kind: str, help_text: str) -> dict:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name [{name}]")
        with self._lock:
            entry = self._families.get(name)
            if entry is None:
                entry = (kind, help_text, {})
                self._families[name] = entry
            elif entry[0] != kind:
                raise ValueError(
                    f"metric [{name}] already registered as {entry[0]}, "
                    f"not {kind}"
                )
            return entry[2]

    @staticmethod
    def _label_key(labels: dict[str, Any]) -> tuple:
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name [{k}]")
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def counter(self, name: str, help_text: str = "", **labels) -> Counter:
        series = self._family(name, "counter", help_text)
        key = self._label_key(labels)
        with self._lock:
            inst = series.get(key)
            if inst is None:
                inst = series[key] = Counter()
            return inst

    def gauge(
        self,
        name: str,
        help_text: str = "",
        fn: Callable[[], float] | None = None,
        **labels,
    ) -> Gauge:
        series = self._family(name, "gauge", help_text)
        key = self._label_key(labels)
        with self._lock:
            inst = series.get(key)
            if inst is None:
                inst = series[key] = Gauge(fn)
            elif fn is not None:
                inst._fn = fn
            return inst

    def histogram(
        self,
        name: str,
        buckets: tuple[float, ...],
        help_text: str = "",
        **labels,
    ) -> Histogram:
        series = self._family(name, "histogram", help_text)
        key = self._label_key(labels)
        with self._lock:
            inst = series.get(key)
            if inst is None:
                inst = series[key] = Histogram(buckets)
            return inst

    # ------------------------------------------------- rolling windows

    def windowed_histogram(
        self,
        name: str,
        help_text: str = "",
        window_s: float = 60.0,
        interval_s: float = 5.0,
        **labels,
    ) -> WindowedHistogram:
        """A rolling-window histogram surfaced as `stat`-labeled gauge
        samples of family `name` (p50 / p99 / rate) — the `estpu_*_recent`
        exposition shape. Hot paths call ``.record(value)`` on the
        returned object; scrapes and health indicators read the gauges /
        ``snapshot()``. Names must end in `_recent` by convention (and
        `_recent_ms` for millisecond-valued families) so recent-window
        series are recognizable at a glance; the staticcheck catalog rule
        covers them like any other estpu_* instrument."""
        key = (name, self._label_key(labels))
        with self._lock:
            existing = self._windows.get(key)
        if existing is not None:
            return existing
        wh = WindowedHistogram(window_s=window_s, interval_s=interval_s)
        with self._lock:
            raced = self._windows.get(key)
            if raced is not None:
                return raced
            self._windows[key] = wh
        for stat in ("p50", "p99", "rate_per_s"):
            self.gauge(
                name,
                help_text,
                fn=lambda s=stat, w=wh: w.stat(s),
                stat=stat,
                **labels,
            )
        return wh

    def windowed_counter(
        self,
        name: str,
        help_text: str = "",
        window_s: float = 60.0,
        interval_s: float = 5.0,
        **labels,
    ) -> WindowedCounter:
        """A rolling-window counter surfaced as `stat`-labeled gauge
        samples (count / rate_per_s over the trailing window)."""
        key = (name, self._label_key(labels))
        with self._lock:
            existing = self._windows.get(key)
        if existing is not None:
            return existing
        wc = WindowedCounter(window_s=window_s, interval_s=interval_s)
        with self._lock:
            raced = self._windows.get(key)
            if raced is not None:
                return raced
            self._windows[key] = wc
        for stat in ("count", "rate_per_s"):
            self.gauge(
                name,
                help_text,
                fn=lambda s=stat, w=wc: w.stat(s),
                stat=stat,
                **labels,
            )
        return wc

    def window(self, name: str, **labels):
        """The windowed instrument registered under (name, labels), or
        None — the health indicators' read accessor."""
        with self._lock:
            return self._windows.get((name, self._label_key(labels)))

    def windows(self, name: str) -> list[tuple[dict[str, str], Any]]:
        """Every windowed instrument of one family as (labels, window)
        pairs — the multi-label read (e.g. launch outcomes grouped by
        backend AND outcome)."""
        with self._lock:
            return [
                (dict(key), w)
                for (n, key), w in self._windows.items()
                if n == name
            ]

    def window_counts(self, name: str, label: str) -> dict[str, float]:
        """Windowed-counter counts keyed by ONE label's value (e.g.
        transport events by `event`) over the trailing window."""
        with self._lock:
            items = [
                (key, w)
                for (n, key), w in self._windows.items()
                if n == name
            ]
        out: dict[str, float] = {}
        for key, window in items:
            for k, v in key:
                if k == label:
                    out[v] = out.get(v, 0.0) + float(window.count())
        return out

    # -------------------------------------------------------------- views

    def value(self, name: str, **labels) -> float:
        """Current value of one counter/gauge sample (0 when absent) —
        the `_nodes/stats` view accessor."""
        with self._lock:
            entry = self._families.get(name)
            if entry is None:
                return 0.0
            inst = entry[2].get(self._label_key(labels))
        return 0.0 if inst is None else inst.value

    def values(self, name: str) -> dict[tuple, float]:
        """Every labeled sample of a family: {label_items: value}."""
        with self._lock:
            entry = self._families.get(name)
            if entry is None:
                return {}
            items = list(entry[2].items())
        return {key: inst.value for key, inst in items}

    def family(self, name: str) -> tuple[str, str, dict] | None:
        """(kind, help, {label_key: value | histogram snapshot}) of one
        family — the public read for consumers that need histogram
        snapshots (scripts/profile_capture.py's launch-ms summaries)."""
        return self._collect().get(name)

    def label_values(self, name: str, label: str) -> dict[str, float]:
        """Family samples keyed by ONE label's value (counters with a
        single distinguishing label, e.g. decisions by backend)."""
        out: dict[str, float] = {}
        for key, value in self.values(name).items():
            for k, v in key:
                if k == label:
                    out[v] = out.get(v, 0.0) + value
        return out

    # ---------------------------------------------------------- exposition

    def _collect(self) -> dict[str, tuple[str, str, dict]]:
        """{name: (kind, help, {label_key: float | histogram snapshot})}"""
        with self._lock:
            families = {
                name: (kind, help_text, dict(series))
                for name, (kind, help_text, series) in self._families.items()
            }
        out: dict[str, tuple[str, str, dict]] = {}
        for name, (kind, help_text, series) in families.items():
            samples = {}
            for key, inst in series.items():
                samples[key] = (
                    inst.snapshot() if kind == "histogram" else inst.value
                )
            out[name] = (kind, help_text, samples)
        return out

    def to_wire(self, *others: "MetricsRegistry") -> dict:
        """JSON-serializable snapshot of every family (optionally merged
        with other registries) — the federation payload the `metrics_wire`
        cluster action ships so a worker process' instruments re-expose at
        the coordinator's `GET /_metrics` (wrap the result in
        WireRegistrySnapshot with a `node` label)."""
        merged = _merge_collected(
            [registry._collect() for registry in (self, *others)]
        )
        return {
            name: {
                "kind": kind,
                "help": help_text,
                "samples": [
                    [[list(kv) for kv in key], sample]
                    for key, sample in samples.items()
                ],
            }
            for name, (kind, help_text, samples) in merged.items()
        }

    def exposition(self, *others) -> str:
        """The Prometheus text format 0.0.4 rendering of every family —
        optionally merged with other registries (the node merges its own
        with the replication gateway's and each cluster node's; samples
        that collide on (name, labels) sum, so per-node series should
        carry a distinguishing label). `others` accepts anything with a
        `_collect()` view, including WireRegistrySnapshot (remote
        registries shipped over the wire)."""
        merged = _merge_collected(
            [registry._collect() for registry in (self, *others)]
        )
        lines: list[str] = []
        for name, (kind, help_text, samples) in sorted(merged.items()):
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for key, sample in sorted(samples.items()):
                labels = ",".join(
                    f'{k}="{_escape_label(v)}"' for k, v in key
                )
                if kind == "histogram":
                    cumulative = 0
                    for bound_str, count in sample["buckets"].items():
                        cumulative += count
                        le = (labels + "," if labels else "") + (
                            f'le="{bound_str}"'
                        )
                        lines.append(f"{name}_bucket{{{le}}} {cumulative}")
                    cumulative += sample["inf"]
                    le = (labels + "," if labels else "") + 'le="+Inf"'
                    lines.append(f"{name}_bucket{{{le}}} {cumulative}")
                    suffix = f"{{{labels}}}" if labels else ""
                    lines.append(
                        f"{name}_sum{suffix} {_format_value(sample['sum'])}"
                    )
                    lines.append(f"{name}_count{suffix} {sample['count']}")
                else:
                    suffix = f"{{{labels}}}" if labels else ""
                    lines.append(
                        f"{name}{suffix} {_format_value(sample)}"
                    )
        return "\n".join(lines) + "\n"


def _merge_collected(
    collected: list[dict[str, tuple[str, str, dict]]],
) -> dict[str, tuple[str, str, dict]]:
    """Fold several `_collect()` views into one family map: samples that
    collide on (name, labels) sum (histograms bucket-wise); families that
    collide on name with a different kind keep the first registration."""
    merged: dict[str, tuple[str, str, dict]] = {}
    for families in collected:
        for name, (kind, help_text, samples) in families.items():
            entry = merged.get(name)
            if entry is None:
                merged[name] = (kind, help_text, dict(samples))
                continue
            if entry[0] != kind:  # conflicting kinds: keep the first
                continue
            for key, sample in samples.items():
                prior = entry[2].get(key)
                if prior is None:
                    entry[2][key] = sample
                elif kind == "histogram":
                    entry[2][key] = {
                        "buckets": {
                            b: prior["buckets"].get(b, 0) + c
                            for b, c in sample["buckets"].items()
                        },
                        "inf": prior["inf"] + sample["inf"],
                        "sum": prior["sum"] + sample["sum"],
                        "count": prior["count"] + sample["count"],
                    }
                else:
                    entry[2][key] = prior + sample
    return merged


class WireRegistrySnapshot:
    """Re-exposes a remote registry's wire families (`to_wire` output) in
    `exposition()` merges, stamping extra labels onto every sample — the
    federation `node` label that keeps one worker's series from colliding
    with another's at the coordinator scrape."""

    def __init__(self, families: dict | None, **labels):
        self.families = families or {}
        self.labels = {k: str(v) for k, v in labels.items()}

    def _collect(self) -> dict[str, tuple[str, str, dict]]:
        out: dict[str, tuple[str, str, dict]] = {}
        for name, fam in self.families.items():
            samples: dict = {}
            for key, sample in fam.get("samples", ()):
                labels = {str(k): str(v) for k, v in key}
                labels.update(self.labels)
                samples[tuple(sorted(labels.items()))] = sample
            out[name] = (
                str(fam.get("kind", "counter")),
                str(fam.get("help", "")),
                samples,
            )
        return out


class _CollectedView:
    """A pre-built `_collect()` view (exposition merge input)."""

    def __init__(self, families: dict[str, tuple[str, str, dict]]):
        self._families = families

    def _collect(self) -> dict[str, tuple[str, str, dict]]:
        return self._families


def fold_cluster_counters(
    snapshots: list[WireRegistrySnapshot],
    label: str = "node",
    value: str = "_cluster",
) -> _CollectedView:
    """Cluster-total series for a federated scrape: every COUNTER sample
    of the per-node snapshots sums into one `node="_cluster"` sample per
    (family, labels). Samples whose original key already carried the fold
    label are skipped — they are per-node by construction and folding
    them would double-count across the label dimension. Gauges and
    histograms stay per-node only (a summed gauge is not a meaningful
    cluster value)."""
    totals: dict[str, tuple[str, str, dict]] = {}
    for snap in snapshots:
        for name, fam in snap.families.items():
            if fam.get("kind") != "counter":
                continue
            for key, sample in fam.get("samples", ()):
                labels = {str(k): str(v) for k, v in key}
                if label in labels:
                    continue
                labels[label] = value
                fkey = tuple(sorted(labels.items()))
                entry = totals.setdefault(
                    name, ("counter", str(fam.get("help", "")), {})
                )
                entry[2][fkey] = entry[2].get(fkey, 0.0) + float(sample)
    return _CollectedView(totals)


# Instrument catalog: every estpu_* instrument in the codebase, its
# kind, and the `_nodes/stats` section that renders it. This is the
# machine-checked contract (staticcheck registry-metric rule) that keeps
# `GET /_metrics` (automatic: every registered family is exposed) and
# `GET /_nodes/stats` (hand-built views) over the SAME instruments: a
# new instrument must be cataloged with its stats section, a renamed one
# must update its catalog entry, and a dead entry fails the gate.
CATALOG = {
    "estpu_exec_planner_decisions_total": ("counter", "exec.planner"),
    "estpu_exec_batcher_batches_total": ("counter", "exec.batcher"),
    "estpu_exec_batcher_requests_total": ("counter", "exec.batcher"),
    "estpu_exec_batcher_coalesced_requests_total": (
        "counter",
        "exec.batcher",
    ),
    "estpu_exec_batcher_queue_cancellations_total": (
        "counter",
        "exec.batcher",
    ),
    "estpu_exec_batcher_shed_total": ("counter", "exec.batcher"),
    "estpu_exec_batcher_retried_individually_total": (
        "counter",
        "exec.batcher",
    ),
    "estpu_exec_batcher_groups_quarantined_total": (
        "counter",
        "exec.batcher",
    ),
    "estpu_exec_batcher_quarantine_hits_total": ("counter", "exec.batcher"),
    "estpu_exec_batcher_occupancy": ("histogram", "exec.batcher"),
    "estpu_exec_batcher_queue_wait_ms": ("histogram", "exec.batcher"),
    "estpu_exec_batcher_queued": ("gauge", "exec.batcher"),
    "estpu_device_launches_total": ("counter", "device"),
    "estpu_device_compile_total": ("counter", "device"),
    "estpu_device_compile_ms_total": ("counter", "device"),
    "estpu_device_h2d_bytes_total": ("counter", "device"),
    "estpu_device_padded_tiles_total": ("counter", "device"),
    "estpu_device_actual_tiles_total": ("counter", "device"),
    "estpu_device_padding_waste_ratio": ("histogram", "device"),
    "estpu_device_blockmax_pruned_tile_fraction": ("histogram", "device"),
    # Device observability (ISSUE 14, obs/device.py): per-launch wall
    # times split queue (dispatch return) vs execute (block_until_ready)
    # per backend/plan class — the split is honest only on real devices
    # (XLA:CPU executes synchronously inside dispatch); real-XLA-compile
    # retraces per plan class (a compile during a launch whose plan key
    # was already seen — the shape-polymorphism alarm); and the HBM
    # ledger's per-(label, index) resident bytes + lifetime peak.
    "estpu_launch_ms": ("histogram", "device"),
    "estpu_device_retraces_total": ("counter", "device.compile"),
    "estpu_hbm_bytes": ("gauge", "device.hbm"),
    "estpu_hbm_high_watermark_bytes": ("gauge", "device.hbm"),
    # Packed multi-tenant execution (exec/packed.py): one launch scores
    # many small indices' lanes against a shared plane.
    "estpu_packed_launches_total": ("counter", "exec.packed"),
    "estpu_packed_lanes_total": ("counter", "exec.packed"),
    "estpu_packed_plane_rebuilds_total": ("counter", "exec.packed"),
    "estpu_packed_fallback_solo_total": ("counter", "exec.packed"),
    "estpu_packed_tenants_per_launch": ("histogram", "exec.packed"),
    "estpu_packed_lanes_per_launch": ("histogram", "exec.packed"),
    "estpu_packed_plane_docs": ("gauge", "exec.packed"),
    "estpu_packed_plane_tenants": ("gauge", "exec.packed"),
    # SPMD mesh serving (parallel/mesh_serving.py): one-launch servings by
    # request shape, and fallbacks to the host-loop coordinator by reason
    # (ineligible_shape, sort_shape, agg_shape, nested, breaker,
    # non_uniform_plan, execute_error) — a silent mesh decline is a bug.
    "estpu_mesh_served_total": ("counter", "mesh_serving"),
    "estpu_mesh_fallback_total": ("counter", "mesh_serving"),
    # Delta-scaled refresh (ROADMAP item 4): shard segments re-packed vs
    # served from unchanged buffers per mesh refresh, and device planes
    # re-uploaded vs shared with the previous snapshot (field-granular
    # upload skipping in tiles.pack_segment_delta).
    "estpu_mesh_segments_packed_total": ("counter", "mesh_serving"),
    "estpu_mesh_segments_reused_total": ("counter", "mesh_serving"),
    "estpu_mesh_field_planes_packed_total": ("counter", "mesh_serving"),
    "estpu_mesh_field_planes_reused_total": ("counter", "mesh_serving"),
    # Engine refresh/merge accounting (index/engine.py; the reference's
    # RefreshStats/MergeStats): totals + wall-clock ms + docs moved by
    # posting-concatenation merges.
    "estpu_refresh_total": ("counter", "indices.refresh"),
    "estpu_refresh_ms_total": ("counter", "indices.refresh"),
    "estpu_merge_total": ("counter", "indices.merges"),
    "estpu_merge_docs_moved_total": ("counter", "indices.merges"),
    "estpu_merge_ms_total": ("counter", "indices.merges"),
    # Analysis-call accounting (analysis/analyzers.py): every tokenize/
    # analyze invocation — the hook that makes "merges never re-tokenize"
    # a measured invariant (tests/test_merge_concat.py, cfg10_ingest).
    "estpu_analysis_calls_total": ("counter", "indices.analysis"),
    # Filter/bitset cache (index/filter_cache.py): device-resident mask
    # planes for repeated filter-context subtrees — the IndicesQueryCache
    # analog, surfaced under `_nodes/stats` indices.filter_cache.
    "estpu_ann_builds_total": ("counter", "search.ann"),
    "estpu_ann_evictions_total": ("counter", "search.ann"),
    "estpu_ann_searches_total": ("counter", "search.ann"),
    "estpu_ann_probes_total": ("counter", "search.ann"),
    "estpu_ann_candidate_fraction": ("histogram", "search.ann"),
    "estpu_ann_recall_gate_total": ("counter", "search.ann"),
    "estpu_ann_bytes_resident": ("gauge", "search.ann"),
    "estpu_ann_partitions_resident": ("gauge", "search.ann"),
    "estpu_ann_centroids_resident": ("gauge", "search.ann"),
    "estpu_filter_cache_hits_total": ("counter", "indices.filter_cache"),
    "estpu_filter_cache_misses_total": ("counter", "indices.filter_cache"),
    "estpu_filter_cache_admissions_total": (
        "counter",
        "indices.filter_cache",
    ),
    "estpu_filter_cache_evictions_total": (
        "counter",
        "indices.filter_cache",
    ),
    "estpu_filter_cache_mask_reuse_total": (
        "counter",
        "indices.filter_cache",
    ),
    "estpu_filter_cache_bytes_resident": ("gauge", "indices.filter_cache"),
    "estpu_filter_cache_entries": ("gauge", "indices.filter_cache"),
    "estpu_request_cache_hits_total": ("counter", "indices.request_cache"),
    "estpu_request_cache_misses_total": (
        "counter",
        "indices.request_cache",
    ),
    "estpu_request_cache_evictions_total": (
        "counter",
        "indices.request_cache",
    ),
    "estpu_request_cache_entries": ("gauge", "indices.request_cache"),
    "estpu_faults_armed": ("gauge", "faults"),
    "estpu_traces_buffered": ("gauge", "obs"),
    "estpu_search_resilience_total": ("counter", "search_resilience"),
    "estpu_cluster_search_resilience_total": (
        "counter",
        "replication.search_resilience",
    ),
    "estpu_replication_gateway_total": ("counter", "replication.gateway"),
    # Control-plane stepper errors (cluster/cluster.py, cluster/procs.py):
    # a step that raised and was swallowed by a background loop — counted
    # so a wedged control plane is visible in `_nodes/stats`.
    "estpu_cluster_step_errors_total": ("counter", "replication.stepper"),
    # TCP transport (cluster/tcp_transport.py) + the in-memory hub's
    # shared deadline counter: connection/reconnect/handshake/frame/
    # timeout instruments, surfaced under replication.transport.
    "estpu_transport_connections_total": ("counter", "replication.transport"),
    "estpu_transport_reconnects_total": ("counter", "replication.transport"),
    "estpu_transport_handshake_rejects_total": (
        "counter",
        "replication.transport",
    ),
    "estpu_transport_send_timeouts_total": (
        "counter",
        "replication.transport",
    ),
    "estpu_transport_frames_total": ("counter", "replication.transport"),
    "estpu_transport_frame_bytes_total": ("counter", "replication.transport"),
    "estpu_transport_open_connections": ("gauge", "replication.transport"),
    # Graceful-shutdown drain barriers entered (cluster/tcp_transport.py
    # drain(): SIGTERM'd workers waiting out their in-flight requests).
    "estpu_transport_drains_total": ("counter", "replication.transport"),
    # Cluster-scope observability fan-in (cluster/transport.scatter_nodes
    # + the node_stats / metrics_wire / trace_fragment / hot_threads wire
    # actions): scatter rounds by action, named per-node failures,
    # wall-clock fan latency, trace-fragment spans shipped from / spliced
    # at nodes, and hot-threads stack snapshots taken by this process.
    "estpu_nodes_stats_fanouts_total": ("counter", "obs.cluster"),
    "estpu_nodes_stats_fan_failures_total": ("counter", "obs.cluster"),
    "estpu_nodes_stats_fan_latency_ms": ("histogram", "obs.cluster"),
    "estpu_trace_fragments_shipped_total": ("counter", "obs.cluster"),
    "estpu_trace_fragments_collected_total": ("counter", "obs.cluster"),
    "estpu_hot_threads_samples_total": ("counter", "obs.cluster"),
    # Rolling-window (`estpu_*_recent`) instruments (ISSUE 15): every
    # cumulative instrument above answers "since boot"; these answer
    # "right now" — the health indicators' inputs, exposed as
    # `stat`-labeled gauge series (p50/p99/rate_per_s for histograms,
    # count/rate_per_s for counters) over a trailing 60s window.
    "estpu_rest_latency_recent_ms": ("windowed_histogram", "obs.recent"),
    "estpu_exec_batcher_queue_wait_recent_ms": (
        "windowed_histogram",
        "exec.batcher",
    ),
    "estpu_exec_batcher_shed_recent": ("windowed_counter", "exec.batcher"),
    "estpu_device_launch_recent": ("windowed_counter", "device"),
    "estpu_filter_cache_evictions_recent": (
        "windowed_counter",
        "indices.filter_cache",
    ),
    "estpu_ann_evictions_recent": ("windowed_counter", "search.ann"),
    "estpu_transport_events_recent": (
        "windowed_counter",
        "replication.transport",
    ),
    # Per-peer attribution of the trailing window's send timeouts
    # (cluster/tcp_transport.py): the transport health indicator reads
    # these to NAME the slow/wedged peer in a brownout diagnosis.
    "estpu_transport_peer_events_recent": (
        "windowed_counter",
        "replication.transport",
    ),
    # Whole-gateway-op latency (retries + backoff included) by op class
    # (cluster/gateway.py): the middle term of the bench's per-hop
    # http -> gateway -> shard split over the socketed topology.
    "estpu_gateway_latency_recent_ms": (
        "windowed_histogram",
        "replication.gateway",
    ),
    # Shard-side search execution latency (cluster/cluster.py,
    # _on_shard_search): the innermost term of the per-hop split — what
    # the shard owner spent executing, net of every wire/queue cost.
    "estpu_shard_exec_latency_recent_ms": (
        "windowed_histogram",
        "replication.search",
    ),
    # Health report (obs/health.py, GET /_health_report): report rounds
    # and the last-computed status per indicator (0 green / 1 yellow /
    # 2 red), surfaced under `_nodes/stats → health`.
    "estpu_health_reports_total": ("counter", "health"),
    "estpu_health_status": ("gauge", "health"),
    # Query insights ring (obs/insights.py, GET /_insights/queries): the
    # structured top-N slowest-searches sample fed from the slowlog's
    # SearchResponse.phases hook.
    "estpu_insights_recorded_total": ("counter", "obs.insights"),
    "estpu_insights_entries": ("gauge", "obs.insights"),
    # Per-tenant QoS lanes (exec/qos.py): windowed per-lane cost/wait
    # accounting behind weighted deficit-round-robin drain and weighted
    # shedding; the exec_saturation indicator names tenants from these.
    "estpu_qos_lanes": ("gauge", "exec.qos"),
    "estpu_qos_shed_total": ("counter", "exec.qos"),
    "estpu_qos_shed_recent": ("windowed_counter", "exec.qos"),
    "estpu_qos_queue_wait_recent_ms": (
        "windowed_histogram",
        "exec.qos",
    ),
    "estpu_qos_lane_cost_recent_ms": ("windowed_counter", "exec.qos"),
    # Async search (exec/async_search.py): the stored progressive-search
    # store and its per-fold reduce timing.
    "estpu_async_searches_total": ("counter", "exec.async_search"),
    "estpu_async_partials_served_total": ("counter", "exec.async_search"),
    "estpu_async_expired_total": ("counter", "exec.async_search"),
    "estpu_async_running": ("gauge", "exec.async_search"),
    "estpu_async_stored": ("gauge", "exec.async_search"),
    "estpu_async_reduce_recent_ms": (
        "windowed_histogram",
        "exec.async_search",
    ),
    # Self-driving remediation (cluster/remediation.py): rounds planned,
    # actions executed, per-attempt failures (the chaos arc's counter),
    # suppressions (hysteresis/cooldown/cap/advisory), plus the trailing
    # window's action count and per-round wall cost (the quiet-cluster
    # overhead gate in bench cfg16_remediation).
    "estpu_remediation_ticks_total": ("counter", "remediation"),
    "estpu_remediation_actions_total": ("counter", "remediation"),
    "estpu_remediation_failures_total": ("counter", "remediation"),
    "estpu_remediation_suppressed_total": ("counter", "remediation"),
    "estpu_remediation_actions_recent": ("windowed_counter", "remediation"),
    "estpu_remediation_tick_recent_ms": (
        "windowed_histogram",
        "remediation",
    ),
    # Per-index write rate over the trailing window (node.py write
    # chokepoint): the lifecycle loop schedules background force-merges
    # only when an index went quiet.
    "estpu_index_writes_recent": ("windowed_counter", "indices"),
    # ANN cache lookup outcomes at the get_or_build sites (index/ann.py):
    # the remediation budget loop and incident capsules read a TRUE hit
    # rate instead of leaning on the eviction window (PR-18 residue).
    "estpu_ann_cache_hits_total": ("counter", "search.ann"),
    "estpu_ann_cache_misses_total": ("counter", "search.ann"),
    "estpu_ann_cache_events_recent": ("windowed_counter", "search.ann"),
    # Flight recorder + incident autopsy (obs/recorder.py +
    # obs/incidents.py, GET /_incidents): frames recorded on the health
    # poll cadence, frames resident in the bounded ring, capsules frozen
    # (auto triggers + manual grabs), incidents resolved back to green,
    # and the open-incident count.
    "estpu_recorder_frames_total": ("counter", "incidents"),
    "estpu_recorder_frames": ("gauge", "incidents"),
    "estpu_incident_captures_total": ("counter", "incidents"),
    "estpu_incident_resolved_total": ("counter", "incidents"),
    "estpu_incident_open": ("gauge", "incidents"),
}

# Pow-2-ish bounds for the padding-waste ratio and occupancy/wait shapes.
PADDING_RATIO_BUCKETS = (0.0, 0.05, 0.1, 0.2, 0.3, 0.5, 0.7, 0.9)
# Fraction of a worklist the two-phase block-max prune dropped.
BLOCKMAX_PRUNE_BUCKETS = (0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99)
OCCUPANCY_BUCKETS = tuple(float(1 << i) for i in range(9))  # 1..256
QUEUE_WAIT_MS_BUCKETS = (
    0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 1024.0,
)
# Wall-clock latency of one cluster-wide stats/obs scatter round; the
# top bounds cover a fan that rode its per-send deadline out.
NODES_FAN_LATENCY_MS_BUCKETS = (
    1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0,
)
# Per-launch queue/execute wall times: sub-ms dispatch up through
# compile-dominated first launches.
LAUNCH_MS_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 512.0,
    2048.0,
)


class DeviceInstruments:
    """Launch-site instruments over one registry.

    ``launch(kind, plan_key, elapsed_s)`` counts every kernel launch; the
    FIRST launch of a given plan_key is recorded as the XLA compile for
    its plan class (jit compiles on first call of a new static shape, so
    first-launch wall time is compile-dominated — the honest in-band
    measure without reaching into XLA internals). Plan classes are
    labeled by the spec kind (bounded cardinality), never the full spec.

    ``timed(kind, plan_key, backend)`` is the per-launch timing wrapper
    (ISSUE 14): it brackets the kernel dispatch so wall time splits into
    queue (dispatch return) vs execute (block_until_ready), feeds the
    ``estpu_launch_ms{plan_class,backend,phase}`` histograms, and arms
    the obs/device.py compile-census attribution — a REAL XLA compile
    observed during a launch whose plan key was already seen counts as a
    retrace (``estpu_device_retraces_total{plan_class}``), the alarm for
    accidental shape-polymorphism regressions. The queue/execute split
    is honest only on real devices: XLA:CPU executes synchronously
    inside dispatch, so there queue absorbs the work and execute ~0.
    """

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self._lock = threading.Lock()
        self._seen: set = set()
        # Real-compile census per plan class (fed by obs/device.py's
        # jax.monitoring listener through timed() windows):
        # kind -> {"compiles": int, "retraces": int, "compile_s": float}
        self._census: dict[str, dict[str, float]] = {}

    def launch(
        self,
        kind: str,
        plan_key: Any,
        elapsed_s: float,
        backend: str = "device",
        queue_s: float | None = None,
    ) -> bool:
        """Record one launch. Returns True when this was the plan key's
        FIRST launch (the inferred-compile signal `profile: true` device
        blocks report as a compile miss)."""
        self.registry.counter(
            "estpu_device_launches_total",
            "Kernel launches by plan class",
            plan_class=kind,
        ).inc()
        with self._lock:
            first = plan_key not in self._seen
            if first:
                self._seen.add(plan_key)
        if first:
            self.registry.counter(
                "estpu_device_compile_total",
                "XLA compiles (first launch of a new plan shape)",
                plan_class=kind,
            ).inc()
            self.registry.counter(
                "estpu_device_compile_ms_total",
                "Wall-clock ms spent in first (compiling) launches",
                plan_class=kind,
            ).inc(elapsed_s * 1e3)
        self.launch_outcome(backend, "ok")
        if queue_s is not None:
            execute_s = max(0.0, elapsed_s - queue_s)
            self._launch_hist(kind, backend, "queue").observe(queue_s * 1e3)
            self._launch_hist(kind, backend, "execute").observe(
                execute_s * 1e3
            )
        else:
            # Untimed site: the whole elapsed is one total-phase sample,
            # so every backend's latency shape is in the histogram even
            # where the dispatch/block split is not instrumented.
            self._launch_hist(kind, backend, "total").observe(
                elapsed_s * 1e3
            )
        return first

    def launch_outcome(self, backend: str, outcome: str) -> None:
        """Per-backend launch outcomes over the trailing window (the
        `device_compile`/`exec_saturation` indicators' error-rate input):
        every completed launch records "ok"; a timed window that raises
        records "error"."""
        self.registry.windowed_counter(
            "estpu_device_launch_recent",
            "Kernel-launch outcomes per backend over the trailing window",
            backend=backend,
            outcome=outcome,
        ).inc()

    def _launch_hist(self, kind: str, backend: str, phase: str) -> Histogram:
        return self.registry.histogram(
            "estpu_launch_ms",
            LAUNCH_MS_BUCKETS,
            "Per-launch wall ms by plan class/backend, split queue "
            "(dispatch return) vs execute (block_until_ready); the split "
            "is honest only on real devices — XLA:CPU runs inside "
            "dispatch",
            plan_class=kind,
            backend=backend,
            phase=phase,
        )

    def timed(
        self, kind: str, plan_key: Any, backend: str = "device"
    ) -> "_TimedLaunch":
        """Context manager for one instrumented launch: call
        ``out = t.dispatched(out)`` right after the kernel call — it
        records the queue split, blocks until the device finishes, and
        returns the ready outputs."""
        return _TimedLaunch(self, kind, plan_key, backend)

    def seen(self, plan_key: Any) -> bool:
        with self._lock:
            return plan_key in self._seen

    def _note_retrace(
        self, kind: str, compiles: int, compile_s: float, retrace: bool
    ) -> None:
        """Census write-back from a timed launch window."""
        with self._lock:
            entry = self._census.setdefault(
                kind, {"compiles": 0, "retraces": 0, "compile_s": 0.0}
            )
            entry["compiles"] += compiles
            entry["compile_s"] += compile_s
            if retrace:
                entry["retraces"] += compiles
        if retrace:
            self.registry.counter(
                "estpu_device_retraces_total",
                "XLA compiles observed on a plan key's NON-first launch "
                "— the plan key failed to capture a varying shape "
                "(shape-polymorphism regression alarm)",
                plan_class=kind,
            ).inc(compiles)
            from . import device as _device

            _device.note_retraces(compiles)

    def h2d(self, arrays: Any) -> int:
        """Host→device transfer bytes: the numpy leaves staged for upload
        by this launch. Returns the byte count (profile device blocks)."""
        try:
            import jax

            nbytes = sum(
                getattr(leaf, "nbytes", 0)
                for leaf in jax.tree.leaves(arrays)
            )
        # staticcheck: ignore[broad-except] H2D byte accounting is best-effort observability; fall back to a plain .nbytes read
        except Exception:
            nbytes = getattr(arrays, "nbytes", 0)
        if nbytes:
            self.registry.counter(
                "estpu_device_h2d_bytes_total",
                "Host-to-device plan-array bytes staged at launch sites",
            ).inc(float(nbytes))
        return int(nbytes)

    def padding(self, actual_tiles: int, padded_tiles: int) -> None:
        """Padding waste of one coalesced launch: padded worklist tiles
        vs. the tiles the lanes actually needed."""
        padded_tiles = max(1, int(padded_tiles))
        waste = max(0.0, 1.0 - float(actual_tiles) / padded_tiles)
        self.registry.counter(
            "estpu_device_padded_tiles_total",
            "Worklist tiles launched (after pad/coalesce)",
        ).inc(float(padded_tiles))
        self.registry.counter(
            "estpu_device_actual_tiles_total",
            "Worklist tiles the lanes actually required",
        ).inc(float(actual_tiles))
        self.registry.histogram(
            "estpu_device_padding_waste_ratio",
            PADDING_RATIO_BUCKETS,
            "Per-coalesced-launch padding waste ratio",
        ).observe(waste)

    def blockmax_pruned(self, fraction: float) -> None:
        """Per-query fraction of worklist tiles a two-phase block-max
        execution pruned before the exact launch (0 = kept everything) —
        prune effectiveness, observable in production at every two-phase
        launch site (ops/bm25_device.execute_batch_blockmax[_conj])."""
        self._prune_hist().observe(min(1.0, max(0.0, float(fraction))))

    def _prune_hist(self) -> Histogram:
        return self.registry.histogram(
            "estpu_device_blockmax_pruned_tile_fraction",
            BLOCKMAX_PRUNE_BUCKETS,
            "Per-query fraction of worklist tiles pruned by two-phase "
            "block-max execution",
        )

    # ------------------------------------------------------------- views

    def compile_count(self) -> int:
        return int(
            sum(
                self.registry.label_values(
                    "estpu_device_compile_total", "plan_class"
                ).values()
            )
        )

    def compile_ms_total(self) -> float:
        return round(
            sum(
                self.registry.label_values(
                    "estpu_device_compile_ms_total", "plan_class"
                ).values()
            ),
            3,
        )

    def padding_waste_pct(self) -> float:
        padded = self.registry.value("estpu_device_padded_tiles_total")
        actual = self.registry.value("estpu_device_actual_tiles_total")
        if padded <= 0:
            return 0.0
        return round(100.0 * (1.0 - actual / padded), 2)

    def retraces_total(self) -> int:
        return int(
            sum(
                self.registry.label_values(
                    "estpu_device_retraces_total", "plan_class"
                ).values()
            )
        )

    def compile_census(self, top_n: int = 8) -> dict[str, Any]:
        """The `device.compile` section of `_nodes/stats`: inferred
        compiles per plan class (first-launch detection), REAL attributed
        XLA compiles + retraces (jax.monitoring census through timed
        windows), and the top-N recompiling classes — any class with a
        nonzero retrace count is the shape-polymorphism alarm firing."""
        with self._lock:
            census = {
                kind: dict(entry) for kind, entry in self._census.items()
            }
        retraced = {
            kind: int(entry["retraces"])
            for kind, entry in census.items()
            if entry["retraces"]
        }
        top = sorted(
            census.items(),
            key=lambda kv: (-kv[1]["compiles"], kv[0]),
        )[:top_n]
        return {
            "compiles_by_plan_class": {
                k: int(v)
                for k, v in sorted(
                    self.registry.label_values(
                        "estpu_device_compile_total", "plan_class"
                    ).items()
                )
            },
            "attributed_xla_compiles": {
                kind: {
                    "compiles": int(entry["compiles"]),
                    "compile_ms": round(entry["compile_s"] * 1e3, 3),
                    "retraces": int(entry["retraces"]),
                }
                for kind, entry in top
            },
            "retraces_total": self.retraces_total(),
            "retraced_plan_classes": {
                k: retraced[k] for k in sorted(retraced)
            },
        }

    def snapshot(self) -> dict[str, Any]:
        """The `_nodes/stats` device section."""
        return {
            "compile_count": self.compile_count(),
            "compile_ms_total": self.compile_ms_total(),
            "compiles_by_plan_class": {
                k: int(v)
                for k, v in sorted(
                    self.registry.label_values(
                        "estpu_device_compile_total", "plan_class"
                    ).items()
                )
            },
            "launches_by_plan_class": {
                k: int(v)
                for k, v in sorted(
                    self.registry.label_values(
                        "estpu_device_launches_total", "plan_class"
                    ).items()
                )
            },
            "h2d_bytes_total": int(
                self.registry.value("estpu_device_h2d_bytes_total")
            ),
            "padding_waste_pct": self.padding_waste_pct(),
            "blockmax_pruned_tile_fraction": self._prune_summary(),
            # Retrace census (ISSUE 14): real attributed XLA compiles +
            # the top-N recompiling classes — `device.compile`.
            "compile": self.compile_census(),
        }

    def _prune_summary(self) -> dict[str, Any]:
        snap = self._prune_hist().snapshot()
        count = snap["count"]
        return {
            "count": int(count),
            "mean": round(snap["sum"] / count, 4) if count else 0.0,
        }


class _NullTimedLaunch:
    """timed() stand-in for uninstrumented paths: same surface, records
    nothing, and dispatched() is a passthrough (device_get blocks later
    anyway)."""

    queue_ms = 0.0
    execute_ms = 0.0
    first = False
    compiles = 0

    def __enter__(self) -> "_NullTimedLaunch":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    @staticmethod
    def dispatched(out: Any) -> Any:
        return out


NULL_TIMED = _NullTimedLaunch()


def timed_launch(instruments, kind: str, plan_key: Any, backend: str):
    """`instruments.timed(...)` or the null stand-in when uninstrumented —
    the one-liner launch sites use so the wrapped/unwrapped code path is
    identical."""
    if instruments is None:
        return NULL_TIMED
    return instruments.timed(kind, plan_key, backend)


class _TimedLaunch:
    """One instrumented kernel launch (DeviceInstruments.timed).

    Usage::

        with instruments.timed(kind, plan_key, backend) as t:
            out = t.dispatched(kernel(...))  # queue split + block

    On exit it records the launch (counts, launch-ms histograms with the
    queue/execute split, first-launch compile inference) and folds the
    compile-census attribution: real XLA compiles that fired on this
    thread during the window (obs/device.py's jax.monitoring listener)
    attribute to this plan class, and count as retraces when the plan
    key had already launched before. A window that raises records
    nothing — a failed launch's timings would poison the histograms."""

    __slots__ = (
        "instruments", "kind", "plan_key", "backend",
        "t0", "t_disp", "t_done", "compiles", "compile_s",
        "_seen_before", "_prev_window", "queue_ms", "execute_ms", "first",
    )

    def __init__(self, instruments, kind, plan_key, backend):
        self.instruments = instruments
        self.kind = kind
        self.plan_key = plan_key
        self.backend = backend
        self.t0 = self.t_disp = self.t_done = 0.0
        self.compiles = 0
        self.compile_s = 0.0
        self.queue_ms = 0.0
        self.execute_ms = 0.0
        self.first = False

    def __enter__(self) -> "_TimedLaunch":
        from . import device as _device

        _device.ensure_compile_listener()
        self._seen_before = self.instruments.seen(self.plan_key)
        self._prev_window = getattr(_device._TLS, "launch_window", None)
        _device._TLS.launch_window = self
        self.t0 = time.monotonic()
        return self

    def note_compile(self, duration_s: float) -> None:
        """Called by the process compile listener on this thread."""
        self.compiles += 1
        self.compile_s += duration_s

    def dispatched(self, out: Any) -> Any:
        """Mark the dispatch return (queue split), then block until the
        device finishes (execute split) and return the ready outputs."""
        import jax

        self.t_disp = time.monotonic()
        out = jax.block_until_ready(out)
        self.t_done = time.monotonic()
        return out

    def __exit__(self, exc_type, exc, tb) -> bool:
        from . import device as _device

        _device._TLS.launch_window = self._prev_window
        if exc is not None:
            # A failed launch records no timings (they would poison the
            # histograms) but DOES count as a windowed error outcome —
            # the recent-failure-rate input health indicators watch.
            self.instruments.launch_outcome(self.backend, "error")
            return False
        now = time.monotonic()
        t_disp = self.t_disp or now
        t_done = self.t_done or now
        queue_s = t_disp - self.t0
        self.queue_ms = round(queue_s * 1e3, 3)
        self.execute_ms = round(max(0.0, t_done - t_disp) * 1e3, 3)
        self.first = self.instruments.launch(
            self.kind,
            self.plan_key,
            t_done - self.t0,
            backend=self.backend,
            queue_s=queue_s,
        )
        if self.compiles:
            self.instruments._note_retrace(
                self.kind,
                self.compiles,
                self.compile_s,
                retrace=self._seen_before,
            )
        return False
