from .painless_lite import CompiledScript, compile_script  # noqa: F401
