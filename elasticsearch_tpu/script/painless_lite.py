"""painless-lite: a vectorizable subset of the reference's script language.

The reference compiles Painless (modules/lang-painless/, ANTLR grammar →
JVM bytecode) and evaluates scripts doc-at-a-time through ScoreScript
(server/.../script/ScoreScript.java). A TPU can't branch per document, so
this engine supports the *expression* subset that covers the score-script
idioms in BASELINE.md configs 4-5 — arithmetic over `_score`, doc values,
params, Math functions, and the x-pack vector functions
(x-pack/plugin/vectors/.../query/ScoreScriptUtils.java:
cosineSimilarity / dotProduct / l2norm) — and evaluates it over ALL
documents at once as array ops.

Compilation path: the (painless-compatible) source is parsed with Python's
`ast` after trivial syntax normalization, validated against a node
whitelist, then evaluated with numpy or jax.numpy arrays bound to `_score`
and `doc[...]` — the same compiled object runs on host (oracle) and under
jit (device), so scripts are traced, not interpreted per doc.

Supported grammar:
    literals, + - * / % unary-, parentheses, ternary `a ? b : c` (via
    Python `b if a else c` after normalization), comparisons,
    _score, params.NAME (or params['NAME']), doc['field'].value,
    Math.log/log10/sqrt/abs/exp/pow/min/max/floor/ceil,
    cosineSimilarity(params.qv, 'field'), dotProduct(...), l2norm(...),
    sigmoid(x), saturation(x, k) (rank-feature helpers).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Any, Callable

_ALLOWED_NODES = (
    ast.Expression,
    ast.BinOp,
    ast.UnaryOp,
    ast.Add,
    ast.Sub,
    ast.Mult,
    ast.Div,
    ast.Mod,
    ast.Pow,
    ast.USub,
    ast.UAdd,
    ast.Call,
    ast.Attribute,
    ast.Subscript,
    ast.Name,
    ast.Constant,
    ast.IfExp,
    ast.Compare,
    ast.Gt,
    ast.GtE,
    ast.Lt,
    ast.LtE,
    ast.Eq,
    ast.NotEq,
    ast.BoolOp,
    ast.And,
    ast.Or,
    ast.Load,
)

_ALLOWED_NAMES = frozenset(
    {
        "_score",
        "params",
        "doc",
        "Math",
        "cosineSimilarity",
        "dotProduct",
        "l2norm",
        "sigmoid",
        "saturation",
        "where",
        "True",
        "False",
    }
)

# `a ? b : c` → `(b) if (a) else (c)`; applied repeatedly for nesting.
_TERNARY_RE = re.compile(r"([^?]+)\?([^:]+):(.+)")


def _normalize(source: str) -> str:
    src = source.strip().rstrip(";")
    # Painless allows `return expr;` for score scripts.
    if src.startswith("return "):
        src = src[len("return ") :].rstrip(";")
    while "?" in src:
        m = _TERNARY_RE.fullmatch(src)
        if not m:
            break
        cond, then, other = m.groups()
        src = f"(({then.strip()}) if ({cond.strip()}) else ({other.strip()}))"
    # Java booleans / null.
    src = re.sub(r"\btrue\b", "True", src)
    src = re.sub(r"\bfalse\b", "False", src)
    return src


class _Params:
    def __init__(self, values: dict[str, Any]):
        self._values = values

    def __getattr__(self, name: str):
        try:
            return self._values[name]
        except KeyError:
            raise ValueError(f"script params has no entry [{name}]") from None

    def __getitem__(self, name: str):
        # Dict lookup only — never getattr, which would resolve real object
        # attributes (dunders) before __getattr__ is consulted.
        try:
            return self._values[name]
        except KeyError:
            raise ValueError(f"script params has no entry [{name}]") from None


class _DocValue:
    def __init__(self, col):
        self.value = col

    @property
    def empty(self):  # doc['f'].empty — NaN means missing
        import numpy as np

        return np.isnan(self.value)


class _Doc:
    def __init__(self, columns: dict[str, Any]):
        self._columns = columns

    def __getitem__(self, field: str) -> _DocValue:
        if field not in self._columns:
            raise ValueError(
                f"No field found for [{field}] in mapping (script doc access)"
            )
        return _DocValue(self._columns[field])


@dataclass(frozen=True)
class CompiledScript:
    """A validated, reusable score expression."""

    source: str
    _tree: ast.Expression

    def evaluate(
        self,
        xp,  # numpy or jax.numpy module
        score,  # [N] array bound to _score
        doc_columns: dict[str, Any],  # field -> [N] numeric column
        vectors: dict[str, Any],  # field -> [N, D] matrix
        params: dict[str, Any],
    ):
        """Evaluate over all docs at once; returns an [N] array."""

        def _vec(field: str):
            if field not in vectors:
                raise ValueError(f"no dense_vector field [{field}]")
            return vectors[field]

        def _matvec(mat, q):
            # On TPU the default f32 matmul precision is bf16 passes; the
            # reference scores vectors in true float32 (x-pack
            # ScoreScriptUtils), so request full-precision MXU passes when
            # the backend supports the kwarg (numpy does not).
            try:
                return xp.matmul(mat, q, precision="highest")
            except TypeError:
                return mat @ q

        def cosine_similarity(qv, field):
            v = _vec(field)
            q = xp.asarray(qv, dtype=xp.float32)
            vnorm = xp.sqrt(xp.sum(v * v, axis=-1))
            qnorm = xp.sqrt(xp.sum(q * q))
            denom = vnorm * qnorm
            return xp.where(denom > 0, _matvec(v, q) / denom, xp.float32(0.0))

        def dot_product(qv, field):
            q = xp.asarray(qv, dtype=xp.float32)
            return _matvec(_vec(field), q)

        def l2norm(qv, field):
            q = xp.asarray(qv, dtype=xp.float32)
            d = _vec(field) - q
            return xp.sqrt(xp.sum(d * d, axis=-1))

        class MathNS:
            log = staticmethod(xp.log)
            log10 = staticmethod(xp.log10)
            sqrt = staticmethod(xp.sqrt)
            abs = staticmethod(xp.abs)
            exp = staticmethod(xp.exp)
            floor = staticmethod(xp.floor)
            ceil = staticmethod(xp.ceil)
            pow = staticmethod(xp.power)
            min = staticmethod(xp.minimum)
            max = staticmethod(xp.maximum)
            E = 2.718281828459045
            PI = 3.141592653589793

        env = {
            "_score": score,
            "params": _Params(params),
            "doc": _Doc(doc_columns),
            "Math": MathNS,
            "cosineSimilarity": cosine_similarity,
            "dotProduct": dot_product,
            "l2norm": l2norm,
            "sigmoid": lambda x: 1.0 / (1.0 + xp.exp(-x)),
            "saturation": lambda x, k: x / (x + k),
            "where": xp.where,
            "True": True,
            "False": False,
        }
        code = compile(self._tree, "<painless-lite>", "eval")
        return eval(code, {"__builtins__": {}}, env)  # noqa: S307


_MATH_MEMBERS = frozenset(
    {
        "log", "log10", "sqrt", "abs", "exp", "floor", "ceil",
        "pow", "min", "max", "E", "PI",
    }
)
_DOC_VALUE_MEMBERS = frozenset({"value", "empty"})


def _validate_access(tree: ast.Expression, source: str) -> None:
    """Whitelist attribute/subscript access shapes.

    The reference's Painless enforces a strict method/field whitelist
    (modules/lang-painless/ PainlessLookup); the analogous rule here is
    structural: the only legal attribute accesses are Math.<member>,
    params.<name>, and doc['field'].value/.empty, and the only legal
    subscripts are doc['field'] / params['name'] with string-constant keys.
    Anything else — in particular any dunder walk like
    `(1.0).__class__.__base__` — is rejected at compile time.
    """

    def fail(why: str) -> None:
        raise ValueError(f"cannot compile script [{source}]: {why}")

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            attr, base = node.attr, node.value
            if attr.startswith("_"):
                fail(f"illegal attribute access [{attr}]")
            if isinstance(base, ast.Name):
                if base.id == "Math":
                    if attr not in _MATH_MEMBERS:
                        fail(f"unknown Math member [{attr}]")
                elif base.id == "params":
                    pass  # params.NAME: any non-underscore name
                else:
                    fail(f"illegal attribute access [{base.id}.{attr}]")
            elif isinstance(base, ast.Subscript):
                sub_base = base.value
                if not (
                    isinstance(sub_base, ast.Name) and sub_base.id == "doc"
                ):
                    fail(f"illegal attribute access [.{attr}]")
                if attr not in _DOC_VALUE_MEMBERS:
                    fail(f"unknown doc-values member [{attr}]")
            else:
                fail(f"illegal attribute access [.{attr}]")
        elif isinstance(node, ast.Subscript):
            base = node.value
            if not (
                isinstance(base, ast.Name) and base.id in ("doc", "params")
            ):
                fail("subscript access is only legal on doc[...] / params[...]")
            key = node.slice
            if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
                fail("doc/params subscript keys must be string literals")
            if key.value.startswith("_"):
                fail(f"illegal subscript key [{key.value}]")


def compile_script(source: str) -> CompiledScript:
    """Parse + validate a painless-lite expression (raises ValueError)."""
    normalized = _normalize(source)
    try:
        tree = ast.parse(normalized, mode="eval")
    except SyntaxError as e:
        raise ValueError(
            f"cannot compile script [{source}]: painless-lite supports "
            f"expressions only ({e.msg})"
        ) from None
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ValueError(
                f"cannot compile script [{source}]: disallowed construct "
                f"[{type(node).__name__}]"
            )
        if isinstance(node, ast.Name) and node.id not in _ALLOWED_NAMES:
            raise ValueError(
                f"cannot compile script [{source}]: unknown identifier "
                f"[{node.id}]"
            )
    _validate_access(tree, source)
    # Ternaries become vectorized selects (`where`) so per-doc conditions
    # work both in numpy and under jit (a Python `if` on a traced array
    # would fail).
    tree = ast.fix_missing_locations(_TernaryToWhere().visit(tree))
    return CompiledScript(source=source, _tree=tree)


class _TernaryToWhere(ast.NodeTransformer):
    def visit_IfExp(self, node: ast.IfExp) -> ast.AST:
        self.generic_visit(node)
        return ast.Call(
            func=ast.Name(id="where", ctx=ast.Load()),
            args=[node.test, node.body, node.orelse],
            keywords=[],
        )
