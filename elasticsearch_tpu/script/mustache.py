"""Mustache-lite: the search-template rendering engine.

The analog of the reference's lang-mustache module
(modules/lang-mustache/src/main/java/org/elasticsearch/script/mustache/
MustacheScriptEngine.java): templates render against `params` before the
result parses as a search body. Supported syntax — the subset the
reference's own docs exercise:

- `{{var}}`            variable (dotted paths), JSON-string-escaped
- `{{{var}}}`          raw (unescaped) variable
- `{{#toJson}}var{{/toJson}}`   value serialized as JSON
- `{{#join}}var{{/join}}`       array joined with ","
- `{{#name}}...{{/name}}`       section: list iteration / truthy guard
- `{{^name}}...{{/name}}`       inverted section (renders when falsy/empty)
- `{{! comment}}`
Inside a list section, `{{.}}` is the current element.
"""

from __future__ import annotations

import json
import re
from typing import Any

_TAG = re.compile(r"\{\{\{(.+?)\}\}\}|\{\{(.+?)\}\}", re.DOTALL)


class TemplateError(ValueError):
    pass


_MISSING = object()  # distinguishes an absent variable from explicit null


def _lookup(stack: list[Any], path: str, default: Any = None) -> Any:
    path = path.strip()
    if path == ".":
        return stack[-1]
    for frame in reversed(stack):
        obj: Any = frame
        found = True
        for part in path.split("."):
            if isinstance(obj, dict) and part in obj:
                obj = obj[part]
            else:
                found = False
                break
        if found:
            return obj
    return default


def _json_escape(value: Any) -> str:
    if value is None:
        return ""
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return json.dumps(value)
    # json.dumps then strip the surrounding quotes: escapes ", \, control
    # chars — the reference's JsonEscapingMustacheFactory behavior.
    return json.dumps(str(value))[1:-1]


def _tokens(template: str):
    """(literal, tag) alternation; tag is (sigil, name) or None."""
    pos = 0
    for m in _TAG.finditer(template):
        if m.start() > pos:
            yield template[pos : m.start()], None
        raw = m.group(1)
        if raw is not None:
            yield "", ("raw", raw.strip())
        else:
            body = m.group(2).strip()
            if body.startswith(("#", "^", "/", "!")):
                yield "", (body[0], body[1:].strip())
            else:
                yield "", ("var", body)
        pos = m.end()
    if pos < len(template):
        yield template[pos:], None


def _parse(tokens: list, i: int, until: str | None, out: list) -> int:
    """Build a node list: str | ("var"/"raw", name) | (kind, name, children)."""
    while i < len(tokens):
        lit, tag = tokens[i]
        i += 1
        if lit:
            out.append(lit)
        if tag is None:
            continue
        sigil, name = tag
        if sigil == "!":
            continue
        if sigil == "/":
            if name != until:
                raise TemplateError(
                    f"unexpected closing tag [{{{{/{name}}}}}]"
                )
            return i
        if sigil in ("#", "^"):
            children: list = []
            i = _parse(tokens, i, name, children)
            out.append((sigil, name, children))
            continue
        out.append((sigil, name))
    if until is not None:
        raise TemplateError(f"unclosed section [{{{{#{until}}}}}]")
    return i


def _section_text(children: list) -> str | None:
    """The literal content of a {{#fn}}var{{/fn}} function section."""
    if len(children) == 1 and isinstance(children[0], str):
        return children[0].strip()
    return None


def _render_nodes(nodes: list, stack: list[Any], out: list[str]) -> None:
    for node in nodes:
        if isinstance(node, str):
            out.append(node)
            continue
        kind = node[0]
        if kind == "var":
            out.append(_json_escape(_lookup(stack, node[1])))
        elif kind == "raw":
            value = _lookup(stack, node[1], _MISSING)
            if value is _MISSING:
                out.append("")  # absent variable: standard mustache empty
            elif isinstance(value, str):
                out.append(value)  # raw = unescaped, verbatim
            else:
                # Non-string values must substitute as VALID JSON —
                # Python's repr ("True", "None", "{'a': 1}") would break
                # the rendered search body at parse time.
                try:
                    out.append(json.dumps(value))
                except (TypeError, ValueError):
                    out.append(str(value))
        elif kind == "#":
            name, children = node[1], node[2]
            if name == "toJson":
                path = _section_text(children)
                if path is None:
                    raise TemplateError("[toJson] takes a single variable")
                out.append(json.dumps(_lookup(stack, path)))
                continue
            if name == "join":
                path = _section_text(children)
                if path is None:
                    raise TemplateError("[join] takes a single variable")
                value = _lookup(stack, path) or []
                out.append(",".join(str(v) for v in value))
                continue
            value = _lookup(stack, name)
            if isinstance(value, list):
                for item in value:
                    stack.append(item)
                    _render_nodes(children, stack, out)
                    stack.pop()
            elif isinstance(value, dict):
                stack.append(value)
                _render_nodes(children, stack, out)
                stack.pop()
            elif value:
                # Standard mustache: a truthy scalar becomes the current
                # context, so {{.}} renders the value itself.
                stack.append(value)
                _render_nodes(children, stack, out)
                stack.pop()
        elif kind == "^":
            value = _lookup(stack, node[1])
            if not value:
                _render_nodes(node[2], stack, out)


def render(template: str, params: dict[str, Any] | None) -> str:
    """Render a mustache template against params; raises TemplateError on
    malformed syntax (the reference 400s these as script compile errors)."""
    nodes: list = []
    _parse(list(_tokens(template)), 0, None, nodes)
    out: list[str] = []
    _render_nodes(nodes, [params or {}], out)
    return "".join(out)
