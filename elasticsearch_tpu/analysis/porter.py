"""Porter stemmer (Porter, 1980) — the `english` analyzer's stem filter.

The analog of the reference's PorterStemFilter inside its english
analyzer (modules/analysis-common EnglishAnalyzerProvider → Lucene
EnglishAnalyzer). Classic algorithm, no extensions; index- and query-time
chains share it, so analysis stays symmetric.
"""

from __future__ import annotations

_VOWELS = set("aeiou")


def _is_consonant(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_consonant(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """The m in [C](VC){m}[V]: count of VC sequences."""
    m = 0
    prev_vowel = False
    for i in range(len(stem)):
        if _is_consonant(stem, i):
            if prev_vowel:
                m += 1
            prev_vowel = False
        else:
            prev_vowel = True
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_consonant(stem, i) for i in range(len(stem)))


def _ends_double_consonant(word: str) -> bool:
    return (
        len(word) >= 2
        and word[-1] == word[-2]
        and _is_consonant(word, len(word) - 1)
    )


def _ends_cvc(word: str) -> bool:
    """*o: stem ends cvc where the final c is not w, x or y."""
    if len(word) < 3:
        return False
    return (
        _is_consonant(word, len(word) - 3)
        and not _is_consonant(word, len(word) - 2)
        and _is_consonant(word, len(word) - 1)
        and word[-1] not in "wxy"
    )


_STEP2 = [
    ("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
    ("izer", "ize"), ("abli", "able"), ("alli", "al"), ("entli", "ent"),
    ("eli", "e"), ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
    ("ator", "ate"), ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
    ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"),
]

_STEP3 = [
    ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
    ("ical", "ic"), ("ful", ""), ("ness", ""),
]

_STEP4 = [
    "al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
    "ment", "ent", "ion", "ou", "ism", "ate", "iti", "ous", "ive", "ize",
]


def stem(word: str) -> str:
    if len(word) <= 2 or not word.isalpha():
        return word
    w = word

    # Step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]

    # Step 1b
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    else:
        flag = False
        if w.endswith("ed") and _has_vowel(w[:-2]):
            w = w[:-2]
            flag = True
        elif w.endswith("ing") and _has_vowel(w[:-3]):
            w = w[:-3]
            flag = True
        if flag:
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif _ends_double_consonant(w) and w[-1] not in "lsz":
                w = w[:-1]
            elif _measure(w) == 1 and _ends_cvc(w):
                w += "e"

    # Step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # Step 2
    for suffix, repl in _STEP2:
        if w.endswith(suffix):
            base = w[: -len(suffix)]
            if _measure(base) > 0:
                w = base + repl
            break

    # Step 3
    for suffix, repl in _STEP3:
        if w.endswith(suffix):
            base = w[: -len(suffix)]
            if _measure(base) > 0:
                w = base + repl
            break

    # Step 4
    for suffix in _STEP4:
        if w.endswith(suffix):
            base = w[: -len(suffix)]
            if suffix == "ion" and (not base or base[-1] not in "st"):
                continue
            if _measure(base) > 1:
                w = base
            break

    # Step 5a
    if w.endswith("e"):
        base = w[:-1]
        m = _measure(base)
        if m > 1 or (m == 1 and not _ends_cvc(base)):
            w = base

    # Step 5b
    if _measure(w) > 1 and _ends_double_consonant(w) and w.endswith("l"):
        w = w[:-1]

    return w


def porter_filter(tokens: list[str]) -> list[str]:
    return [stem(t) for t in tokens]
