from .analyzers import (
    Analyzer,
    AnalysisRegistry,
    KeywordAnalyzer,
    SimpleAnalyzer,
    StandardAnalyzer,
    StopAnalyzer,
    WhitespaceAnalyzer,
    get_analyzer,
)

__all__ = [
    "Analyzer",
    "AnalysisRegistry",
    "KeywordAnalyzer",
    "SimpleAnalyzer",
    "StandardAnalyzer",
    "StopAnalyzer",
    "WhitespaceAnalyzer",
    "get_analyzer",
]
