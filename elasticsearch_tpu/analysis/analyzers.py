"""Text analysis: tokenizers, token filters, analyzers.

Rebuilds the analysis chain of the reference (registry:
server/src/main/java/org/elasticsearch/index/analysis/AnalysisRegistry.java,
built-in chains: modules/analysis-common/) as composable Python callables.
Analysis runs on the host at index/query time; its output (term ids, term
frequencies, field lengths) is what gets packed into device tensors, so the
only contract that matters for score parity is that index-time and query-time
analysis agree.

The standard analyzer approximates Lucene's UAX#29 word segmentation with a
Unicode-aware word regex (alphanumeric runs, keeping digits), followed by
lowercasing. Eastern-language segmentation packs (icu/kuromoji/nori/smartcn in
the reference's plugins/) are out of scope.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

Token = str
TokenFilter = Callable[[list[Token]], list[Token]]

# Hook-counted analysis accounting: every tokenize/analyze invocation —
# Python analyzer chains here, the native ASCII tokenizer at its
# index/segment.py call site — increments this counter, so "the merge does
# no re-tokenization" (index/merge.py, ROADMAP item 4) is a measured
# invariant (tests/test_merge_concat.py, bench cfg10_ingest), not an
# assertion by inspection. A module-global registry: analysis is
# process-wide (analyzers are shared singletons), and the node merges this
# registry into `GET /_metrics` / renders it under `_nodes/stats`
# indices.analysis.
from ..obs.metrics import MetricsRegistry as _MetricsRegistry

ANALYSIS_METRICS = _MetricsRegistry()
ANALYSIS_CALLS = ANALYSIS_METRICS.counter(
    "estpu_analysis_calls_total",
    "Tokenize/analyze entry-point invocations (index + query time)",
)


def analysis_calls_total() -> int:
    """Current analysis-call count (test/bench hook)."""
    return int(ANALYSIS_CALLS.value)

# Unicode word pattern: letters/digits/underscore runs. Lucene's standard
# tokenizer splits on punctuation and whitespace and keeps numerics.
_WORD_RE = re.compile(r"[\w]+", re.UNICODE)
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)

# Lucene's default English stopword set (org.apache.lucene.analysis.en).
ENGLISH_STOPWORDS = frozenset(
    """a an and are as at be but by for if in into is it no not of on or such
    that the their then there these they this to was will with""".split()
)


@dataclass
class Analyzer:
    """A tokenizer plus an ordered chain of token filters."""

    name: str
    tokenizer: Callable[[str], list[Token]]
    filters: list[TokenFilter] = field(default_factory=list)

    def analyze(self, text: str) -> list[Token]:
        ANALYSIS_CALLS.inc()
        tokens = self.tokenizer(text)
        for f in self.filters:
            tokens = f(tokens)
        return tokens

    def _carry_filters(
        self, items: list[tuple[Token, Any]]
    ) -> list[tuple[Token, Any]]:
        """Thread (token, payload) pairs through the filter chain, keeping
        each surviving token's payload (a position, an offset span, ...).

        Three filter shapes: marked drop filters (stopset attribute) keep
        gaps; length-preserving outputs are 1:1 order-preserving maps;
        anything else falls back to per-token application. The single
        implementation behind both positions (phrase matching) and offsets
        (highlighting) so the two can never desynchronize.
        """
        for f in self.filters:
            stopset = getattr(f, "stopset", None)
            if stopset is not None:
                items = [it for it in items if it[0] not in stopset]
                continue
            mapped = f([tok for tok, _ in items])
            if len(mapped) == len(items):
                items = [(m, p) for m, (_, p) in zip(mapped, items)]
                continue
            out = []
            for tok, p in items:
                r = f([tok])
                if r:
                    out.append((r[0], p))
            items = out
        return items

    def analyze_positions(self, text: str) -> tuple[list[tuple[Token, int]], int]:
        """((token, position) pairs, total position span).

        Positions carry through filters with Lucene position-increment
        semantics: a removed token (stop filter) leaves a GAP rather than
        shifting later tokens down — `match_phrase` relies on these gaps
        exactly like Lucene's StopFilter keeps increments. The span is the
        tokenizer's position count (for multi-value position offsets).
        """
        ANALYSIS_CALLS.inc()
        tokens = self.tokenizer(text)
        pairs = self._carry_filters([(t, i) for i, t in enumerate(tokens)])
        return pairs, len(tokens)

    def analyze_offsets(self, text: str) -> list[tuple[Token, int, int]]:
        """(token, char_start, char_end) triples — the highlighter's view
        (Lucene's OffsetAttribute). Offsets always reference the ORIGINAL
        text even through token-mapping filters."""
        ANALYSIS_CALLS.inc()
        spans = _TOKENIZER_SPANS.get(self.tokenizer)
        if spans is None:  # unknown tokenizer: no offset support
            return []
        carried = self._carry_filters(
            [(tok, (s, e)) for tok, s, e in spans(text)]
        )
        return [(tok, s, e) for tok, (s, e) in carried]

    def __call__(self, text: str) -> list[Token]:
        return self.analyze(text)


def _standard_tokenize(text: str) -> list[Token]:
    return _WORD_RE.findall(text)


def _letter_tokenize(text: str) -> list[Token]:
    return _LETTER_RE.findall(text)


def _whitespace_tokenize(text: str) -> list[Token]:
    return text.split()


def _keyword_tokenize(text: str) -> list[Token]:
    return [text] if text else []


_WS_RE = re.compile(r"\S+")


def _spans_from_re(regex):
    def spans(text: str) -> list[tuple[Token, int, int]]:
        return [(m.group(), m.start(), m.end()) for m in regex.finditer(text)]

    return spans


def _keyword_spans(text: str) -> list[tuple[Token, int, int]]:
    return [(text, 0, len(text))] if text else []


# Offset-producing twins of each tokenizer (highlighting needs character
# offsets; the plain tokenizers stay allocation-light for indexing).
_TOKENIZER_SPANS = {}


def lowercase_filter(tokens: list[Token]) -> list[Token]:
    return [t.lower() for t in tokens]


def make_stop_filter(stopwords: Iterable[str]) -> TokenFilter:
    stopset = frozenset(stopwords)

    def stop_filter(tokens: list[Token]) -> list[Token]:
        return [t for t in tokens if t not in stopset]

    # Marks this as a pure drop filter so position-aware analysis can keep
    # gaps without per-token fallback calls.
    stop_filter.stopset = stopset
    return stop_filter


def make_asciifolding_filter() -> TokenFilter:
    import unicodedata

    def fold(tokens: list[Token]) -> list[Token]:
        out = []
        for t in tokens:
            norm = unicodedata.normalize("NFKD", t)
            out.append("".join(c for c in norm if not unicodedata.combining(c)))
        return out

    return fold


_TOKENIZER_SPANS.update(
    {
        _standard_tokenize: _spans_from_re(_WORD_RE),
        _letter_tokenize: _spans_from_re(_LETTER_RE),
        _whitespace_tokenize: _spans_from_re(_WS_RE),
        _keyword_tokenize: _keyword_spans,
    }
)

StandardAnalyzer = Analyzer("standard", _standard_tokenize, [lowercase_filter])
SimpleAnalyzer = Analyzer("simple", _letter_tokenize, [lowercase_filter])
WhitespaceAnalyzer = Analyzer("whitespace", _whitespace_tokenize, [])
KeywordAnalyzer = Analyzer("keyword", _keyword_tokenize, [])
StopAnalyzer = Analyzer(
    "stop", _letter_tokenize, [lowercase_filter, make_stop_filter(ENGLISH_STOPWORDS)]
)

_BUILTIN = {
    a.name: a
    for a in (
        StandardAnalyzer,
        SimpleAnalyzer,
        WhitespaceAnalyzer,
        KeywordAnalyzer,
        StopAnalyzer,
    )
}

def _porter_filter(tokens: list[Token]) -> list[Token]:
    from .porter import stem

    return [stem(t) for t in tokens]


# "english" = standard tokenizer + lowercase + stopwords + porter stem —
# the reference's EnglishAnalyzer chain (analysis-common
# EnglishAnalyzerProvider). Its possessive filter is unnecessary here:
# our word-run tokenizer already splits "runner's" at the apostrophe.
# Index- and query-time chains share the stemmer, so analysis stays
# symmetric.
_BUILTIN["english"] = Analyzer(
    "english",
    _standard_tokenize,
    [
        lowercase_filter,
        make_stop_filter(ENGLISH_STOPWORDS),
        _porter_filter,
    ],
)


def make_shingle_filter(n: int) -> TokenFilter:
    """Word shingles of size n, space-joined — the reference's
    ShingleTokenFilter as used by search_as_you_type's _2gram/_3gram
    subfields (SearchAsYouTypeFieldMapper). Changes token count, so it
    only runs on norms-free fields (full-chain analyze())."""

    def shingles(tokens: list[Token]) -> list[Token]:
        return [
            " ".join(tokens[i : i + n])
            for i in range(len(tokens) - n + 1)
        ]

    return shingles


def make_edge_ngram_filter(min_gram: int = 1, max_gram: int = 20) -> TokenFilter:
    """Per-token edge n-grams — search_as_you_type's _index_prefix
    subfield (the reference's index_prefixes machinery), letting the
    final partial token of a type-ahead query match as a plain term."""

    def edges(tokens: list[Token]) -> list[Token]:
        out = []
        for t in tokens:
            for ln in range(min_gram, min(len(t), max_gram) + 1):
                out.append(t[:ln])
        return out

    return edges


# search_as_you_type subfield chains (index side; queries against the
# base field analyze with plain standard).
_BUILTIN["_sayt_2gram"] = Analyzer(
    "_sayt_2gram", _standard_tokenize, [lowercase_filter, make_shingle_filter(2)]
)
_BUILTIN["_sayt_3gram"] = Analyzer(
    "_sayt_3gram", _standard_tokenize, [lowercase_filter, make_shingle_filter(3)]
)
_BUILTIN["_sayt_prefix"] = Analyzer(
    "_sayt_prefix",
    _standard_tokenize,
    [lowercase_filter, make_edge_ngram_filter(1, 20)],
)


def get_analyzer(name: str) -> Analyzer:
    try:
        return _BUILTIN[name]
    except KeyError:
        raise ValueError(
            f"unknown analyzer [{name}]; available: {sorted(_BUILTIN)}"
        ) from None


class AnalysisRegistry:
    """Per-index analyzer registry supporting custom analyzer definitions.

    Mirrors the role of the reference's AnalysisRegistry: resolve built-in
    analyzers by name and build custom ones from a settings dict
    ({"tokenizer": ..., "filter": [...]})
    """

    _TOKENIZERS = {
        "standard": _standard_tokenize,
        "letter": _letter_tokenize,
        "whitespace": _whitespace_tokenize,
        "keyword": _keyword_tokenize,
    }

    def __init__(self, custom: dict[str, dict] | None = None):
        self._analyzers: dict[str, Analyzer] = dict(_BUILTIN)
        for name, spec in (custom or {}).items():
            self._analyzers[name] = self._build(name, spec)

    def _build(self, name: str, spec: dict) -> Analyzer:
        tokenizer_name = spec.get("tokenizer", "standard")
        try:
            tokenizer = self._TOKENIZERS[tokenizer_name]
        except KeyError:
            raise ValueError(f"unknown tokenizer [{tokenizer_name}]") from None
        filters: list[TokenFilter] = []
        for fname in spec.get("filter", []):
            if fname == "lowercase":
                filters.append(lowercase_filter)
            elif fname == "stop":
                filters.append(make_stop_filter(ENGLISH_STOPWORDS))
            elif fname == "asciifolding":
                filters.append(make_asciifolding_filter())
            elif fname in ("porter_stem", "stemmer"):
                filters.append(_porter_filter)
            else:
                raise ValueError(f"unknown token filter [{fname}]")
        return Analyzer(name, tokenizer, filters)

    def get(self, name: str) -> Analyzer:
        try:
            return self._analyzers[name]
        except KeyError:
            raise ValueError(f"unknown analyzer [{name}]") from None
