"""Text analysis: tokenizers, token filters, analyzers.

Rebuilds the analysis chain of the reference (registry:
server/src/main/java/org/elasticsearch/index/analysis/AnalysisRegistry.java,
built-in chains: modules/analysis-common/) as composable Python callables.
Analysis runs on the host at index/query time; its output (term ids, term
frequencies, field lengths) is what gets packed into device tensors, so the
only contract that matters for score parity is that index-time and query-time
analysis agree.

The standard analyzer approximates Lucene's UAX#29 word segmentation with a
Unicode-aware word regex (alphanumeric runs, keeping digits), followed by
lowercasing. Eastern-language segmentation packs (icu/kuromoji/nori/smartcn in
the reference's plugins/) are out of scope.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Iterable

Token = str
TokenFilter = Callable[[list[Token]], list[Token]]

# Unicode word pattern: letters/digits/underscore runs. Lucene's standard
# tokenizer splits on punctuation and whitespace and keeps numerics.
_WORD_RE = re.compile(r"[\w]+", re.UNICODE)
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)

# Lucene's default English stopword set (org.apache.lucene.analysis.en).
ENGLISH_STOPWORDS = frozenset(
    """a an and are as at be but by for if in into is it no not of on or such
    that the their then there these they this to was will with""".split()
)


@dataclass
class Analyzer:
    """A tokenizer plus an ordered chain of token filters."""

    name: str
    tokenizer: Callable[[str], list[Token]]
    filters: list[TokenFilter] = field(default_factory=list)

    def analyze(self, text: str) -> list[Token]:
        tokens = self.tokenizer(text)
        for f in self.filters:
            tokens = f(tokens)
        return tokens

    def analyze_positions(self, text: str) -> tuple[list[tuple[Token, int]], int]:
        """((token, position) pairs, total position span).

        Positions carry through filters with Lucene position-increment
        semantics: a removed token (stop filter) leaves a GAP rather than
        shifting later tokens down — `match_phrase` relies on these gaps
        exactly like Lucene's StopFilter keeps increments. The span is the
        tokenizer's position count (for multi-value position offsets).
        """
        tokens = self.tokenizer(text)
        span = len(tokens)
        pairs = [(t, i) for i, t in enumerate(tokens)]
        for f in self.filters:
            stopset = getattr(f, "stopset", None)
            if stopset is not None:  # drop filter: keep position gaps
                pairs = [(t, p) for t, p in pairs if t not in stopset]
                continue
            mapped = f([t for t, _ in pairs])
            if len(mapped) == len(pairs):  # 1:1 order-preserving map
                pairs = [(m, p) for m, (_, p) in zip(mapped, pairs)]
                continue
            # Unknown drop/split filter: per-token fallback keeps positions.
            new_pairs = []
            for t, p in pairs:
                out = f([t])
                if out:
                    new_pairs.append((out[0], p))
            pairs = new_pairs
        return pairs, span

    def __call__(self, text: str) -> list[Token]:
        return self.analyze(text)


def _standard_tokenize(text: str) -> list[Token]:
    return _WORD_RE.findall(text)


def _letter_tokenize(text: str) -> list[Token]:
    return _LETTER_RE.findall(text)


def _whitespace_tokenize(text: str) -> list[Token]:
    return text.split()


def _keyword_tokenize(text: str) -> list[Token]:
    return [text] if text else []


def lowercase_filter(tokens: list[Token]) -> list[Token]:
    return [t.lower() for t in tokens]


def make_stop_filter(stopwords: Iterable[str]) -> TokenFilter:
    stopset = frozenset(stopwords)

    def stop_filter(tokens: list[Token]) -> list[Token]:
        return [t for t in tokens if t not in stopset]

    # Marks this as a pure drop filter so position-aware analysis can keep
    # gaps without per-token fallback calls.
    stop_filter.stopset = stopset
    return stop_filter


def make_asciifolding_filter() -> TokenFilter:
    import unicodedata

    def fold(tokens: list[Token]) -> list[Token]:
        out = []
        for t in tokens:
            norm = unicodedata.normalize("NFKD", t)
            out.append("".join(c for c in norm if not unicodedata.combining(c)))
        return out

    return fold


StandardAnalyzer = Analyzer("standard", _standard_tokenize, [lowercase_filter])
SimpleAnalyzer = Analyzer("simple", _letter_tokenize, [lowercase_filter])
WhitespaceAnalyzer = Analyzer("whitespace", _whitespace_tokenize, [])
KeywordAnalyzer = Analyzer("keyword", _keyword_tokenize, [])
StopAnalyzer = Analyzer(
    "stop", _letter_tokenize, [lowercase_filter, make_stop_filter(ENGLISH_STOPWORDS)]
)

_BUILTIN = {
    a.name: a
    for a in (
        StandardAnalyzer,
        SimpleAnalyzer,
        WhitespaceAnalyzer,
        KeywordAnalyzer,
        StopAnalyzer,
    )
}

# "english" = standard tokenizer + lowercase + english stopwords. (The
# reference additionally applies a possessive and porter stemmer; stemming is
# intentionally omitted for round 1 to keep query/index analysis symmetric.)
_BUILTIN["english"] = Analyzer(
    "english",
    _standard_tokenize,
    [lowercase_filter, make_stop_filter(ENGLISH_STOPWORDS)],
)


def get_analyzer(name: str) -> Analyzer:
    try:
        return _BUILTIN[name]
    except KeyError:
        raise ValueError(
            f"unknown analyzer [{name}]; available: {sorted(_BUILTIN)}"
        ) from None


class AnalysisRegistry:
    """Per-index analyzer registry supporting custom analyzer definitions.

    Mirrors the role of the reference's AnalysisRegistry: resolve built-in
    analyzers by name and build custom ones from a settings dict
    ({"tokenizer": ..., "filter": [...]})
    """

    _TOKENIZERS = {
        "standard": _standard_tokenize,
        "letter": _letter_tokenize,
        "whitespace": _whitespace_tokenize,
        "keyword": _keyword_tokenize,
    }

    def __init__(self, custom: dict[str, dict] | None = None):
        self._analyzers: dict[str, Analyzer] = dict(_BUILTIN)
        for name, spec in (custom or {}).items():
            self._analyzers[name] = self._build(name, spec)

    def _build(self, name: str, spec: dict) -> Analyzer:
        tokenizer_name = spec.get("tokenizer", "standard")
        try:
            tokenizer = self._TOKENIZERS[tokenizer_name]
        except KeyError:
            raise ValueError(f"unknown tokenizer [{tokenizer_name}]") from None
        filters: list[TokenFilter] = []
        for fname in spec.get("filter", []):
            if fname == "lowercase":
                filters.append(lowercase_filter)
            elif fname == "stop":
                filters.append(make_stop_filter(ENGLISH_STOPWORDS))
            elif fname == "asciifolding":
                filters.append(make_asciifolding_filter())
            else:
                raise ValueError(f"unknown token filter [{fname}]")
        return Analyzer(name, tokenizer, filters)

    def get(self, name: str) -> Analyzer:
        try:
            return self._analyzers[name]
        except KeyError:
            raise ValueError(f"unknown analyzer [{name}]") from None
