from .breaker import BreakerError, CircuitBreaker
from .request_cache import RequestCache

__all__ = ["BreakerError", "CircuitBreaker", "RequestCache"]
