"""Task registry: running-action tracking, cancellation, timeouts.

The analog of the reference's TaskManager (tasks/TaskManager.java) +
CancellableTask: every search registers a task; cancellation and the
request timeout are polled at kernel-launch boundaries (between segments
and shards) — the TPU analog of the reference polling inside the scoring
loop (search/internal/ContextIndexSearcher.java:91 checkCancelled /
search/query/QueryPhase.java timeout collector): an XLA program itself is
not interruptible, so the check granularity is one segment's launch.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any


class TaskCancelledError(Exception):
    """Raised inside a cancelled task (HTTP 400 task_cancelled_exception)."""


@dataclass
class Task:
    id: str
    action: str
    description: str
    cancellable: bool = True
    # staticcheck: ignore[wallclock-duration] user-facing start_time_in_millis is an epoch timestamp; runtime uses start_mono below
    start_ms: float = field(default_factory=lambda: time.time() * 1000)
    # Monotonic start: running_time_in_nanos must survive wall-clock
    # steps (NTP slew during a long search would otherwise report a
    # negative or wildly wrong runtime).
    start_mono: float = field(default_factory=time.monotonic)
    # The task's current span name (obs/tracing.py mirrors the active
    # span here), surfaced by `GET /_tasks` / `GET /_cat/tasks`.
    span_name: str | None = None
    deadline: float | None = None  # monotonic seconds; None = no timeout
    _cancelled: bool = False
    _timed_out: bool = False
    cancel_reason: str | None = None
    # Cancel listeners: hooks fired synchronously by cancel() so work
    # waiting OUTSIDE a kernel (e.g. a search queued in the exec micro-
    # batcher) can unwind immediately instead of waiting for the next
    # launch-boundary poll. The lock makes register-vs-cancel atomic: a
    # listener can never be lost between the cancelled check and the
    # append (it either lands on the list cancel() will drain, or runs
    # directly because cancellation already happened).
    _cancel_listeners: list = field(default_factory=list)
    _listener_lock: Any = field(default_factory=threading.Lock)

    def add_cancel_listener(self, fn) -> None:
        """Register fn() to run on cancellation (immediately if already
        cancelled)."""
        with self._listener_lock:
            if not self._cancelled:
                self._cancel_listeners.append(fn)
                return
        fn()

    def cancel(self, reason: str = "by user request") -> None:
        with self._listener_lock:
            self._cancelled = True
            self.cancel_reason = reason
            listeners, self._cancel_listeners = self._cancel_listeners, []
        for fn in listeners:
            fn()

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def raise_if_cancelled(self) -> None:
        if self._cancelled:
            raise TaskCancelledError(
                f"task cancelled [{self.cancel_reason}]"
            )

    def check_deadline(self) -> bool:
        """True (and latches timed_out) once the wall-clock budget is
        exhausted — callers stop launching work and return partials."""
        if self.deadline is not None and time.monotonic() > self.deadline:
            self._timed_out = True
        return self._timed_out

    @property
    def timed_out(self) -> bool:
        return self._timed_out

    def to_json(self, detailed: bool = True) -> dict[str, Any]:
        out = {
            "node": self.id.split(":")[0],
            "id": int(self.id.split(":")[1]),
            "type": "transport",
            "action": self.action,
            "start_time_in_millis": int(self.start_ms),
            "running_time_in_nanos": int(
                (time.monotonic() - self.start_mono) * 1e9
            ),
            "cancellable": self.cancellable,
            "cancelled": self._cancelled,
        }
        if self.span_name is not None:
            # Where the task is RIGHT NOW (obs/tracing.py mirrors the
            # active span here): which segment/queue/phase a long search
            # is currently in.
            out["span"] = self.span_name
        if detailed:
            out["description"] = self.description
        return out


class TaskManager:
    """Thread-safe registry of running tasks (tasks/TaskManager.java)."""

    def __init__(self, node_name: str = "node-0"):
        self.node_name = node_name
        self._tasks: dict[str, Task] = {}
        self._lock = threading.Lock()
        self._counter = 0

    def register(
        self,
        action: str,
        description: str = "",
        timeout_s: float | None = None,
        cancellable: bool = True,
    ) -> Task:
        with self._lock:
            self._counter += 1
            task_id = f"{self.node_name}:{self._counter}"
            task = Task(
                id=task_id,
                action=action,
                description=description,
                cancellable=cancellable,
                deadline=(
                    time.monotonic() + timeout_s
                    if timeout_s is not None
                    else None
                ),
            )
            self._tasks[task_id] = task
            return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            self._tasks.pop(task.id, None)

    def get(self, task_id: str) -> Task | None:
        with self._lock:
            return self._tasks.get(task_id)

    def cancel(self, task_id: str, reason: str = "by user request") -> Task | None:
        with self._lock:
            task = self._tasks.get(task_id)
        if task is not None and task.cancellable:
            task.cancel(reason)
        return task

    def list(self, actions: str | None = None) -> list[Task]:
        with self._lock:
            tasks = list(self._tasks.values())
        if actions:
            # ES-style action filter with trailing-* wildcard support.
            pats = [a.strip() for a in actions.split(",")]
            tasks = [
                t
                for t in tasks
                if any(
                    t.action == p
                    or (p.endswith("*") and t.action.startswith(p[:-1]))
                    for p in pats
                )
            ]
        return tasks
