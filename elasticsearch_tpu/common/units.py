"""Time-value parsing shared by every duration-bearing API parameter
(the reference's common/unit/TimeValue)."""

from __future__ import annotations

import re

_DURATION_RE = re.compile(r"^(\d+)(ms|s|m|h|d)$")
_UNIT_S = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration_s(value: str) -> float:
    """ES time value ('100ms', '30s', '1m', '2h', '1d') → seconds.

    Raises ValueError on anything else (callers map to their error type).
    """
    m = _DURATION_RE.match(str(value))
    if not m:
        raise ValueError(f"failed to parse time value [{value}]")
    return int(m.group(1)) * _UNIT_S[m.group(2)]
