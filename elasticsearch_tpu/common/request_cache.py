"""Shard request cache: serialized search responses keyed by request bytes.

The analog of the reference's IndicesRequestCache
(indices/IndicesRequestCache.java:57): size=0 requests (aggregations,
counts) cache their full response, keyed by the canonical request body
plus every shard's refresh generation — so a refresh implicitly
invalidates without any explicit eviction hook, exactly like the
reference keying on the reader's cache helper. Entries store the
serialized JSON string; a hit deserializes a fresh object so callers
can't mutate the cached copy.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Any


class RequestCache:
    def __init__(self, max_entries: int = 256):
        self.max_entries = max_entries
        self._entries: OrderedDict[tuple, str] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def key(index: str, body: dict | None, generations: tuple) -> tuple:
        return (
            index,
            json.dumps(body or {}, sort_keys=True, separators=(",", ":")),
            generations,
        )

    def get(self, key: tuple) -> Any | None:
        with self._lock:
            raw = self._entries.get(key)
            if raw is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
        return json.loads(raw)

    def put(self, key: tuple, response: dict) -> None:
        raw = json.dumps(response, separators=(",", ":"))
        with self._lock:
            self._entries[key] = raw
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def clear(self) -> None:
        """Drop every cached entry (the `_cache/clear` API analog)."""
        with self._lock:
            self._entries.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hit_count": self.hits,
                "miss_count": self.misses,
                "evictions": self.evictions,
            }
