"""Shard request cache: serialized search responses keyed by request bytes.

The analog of the reference's IndicesRequestCache
(indices/IndicesRequestCache.java:57): size=0 requests (aggregations,
counts) cache their full response, keyed by the canonical request body
plus every shard's refresh generation — so a refresh implicitly
invalidates without any explicit eviction hook, exactly like the
reference keying on the reader's cache helper. Entries store the
serialized JSON string; a hit deserializes a fresh object so callers
can't mutate the cached copy.

Hit/miss/eviction accounting writes through the node's metrics registry
(obs/metrics.py) — `_nodes/stats` and `GET /_metrics` render the same
counters.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Any


class RequestCache:
    def __init__(self, max_entries: int = 256, metrics=None):
        self.max_entries = max_entries
        self._entries: OrderedDict[tuple, str] = OrderedDict()
        self._lock = threading.Lock()
        if metrics is None:
            from ..obs.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self._hits = metrics.counter(
            "estpu_request_cache_hits_total", "Shard request cache hits"
        )
        self._misses = metrics.counter(
            "estpu_request_cache_misses_total", "Shard request cache misses"
        )
        self._evictions = metrics.counter(
            "estpu_request_cache_evictions_total",
            "Shard request cache LRU evictions",
        )
        metrics.gauge(
            "estpu_request_cache_entries",
            "Shard request cache live entries",
            fn=lambda: len(self._entries),
        )

    @staticmethod
    def key(index: str, body: dict | None, generations: tuple) -> tuple:
        return (
            index,
            json.dumps(body or {}, sort_keys=True, separators=(",", ":")),
            generations,
        )

    def get(self, key: tuple) -> Any | None:
        with self._lock:
            raw = self._entries.get(key)
            if raw is None:
                self._misses.inc()
                return None
            self._entries.move_to_end(key)
            self._hits.inc()
        return json.loads(raw)

    def put(self, key: tuple, response: dict) -> None:
        raw = json.dumps(response, separators=(",", ":"))
        with self._lock:
            self._entries[key] = raw
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self._evictions.inc()

    # Back-compat accessors (pre-migration attribute names): the values
    # now live on the registry counters.
    @property
    def hits(self) -> int:
        return int(self._hits.value)

    @property
    def misses(self) -> int:
        return int(self._misses.value)

    @property
    def evictions(self) -> int:
        return int(self._evictions.value)

    def clear(self, index_key=None) -> int:
        """Drop cached entries (the `_cache/clear` API analog): all of
        them, or only one index's (entries key on the index uuid as
        their first component). Returns the number dropped."""
        with self._lock:
            if index_key is None:
                n = len(self._entries)
                self._entries.clear()
                return n
            keys = [k for k in self._entries if k[0] == index_key]
            for k in keys:
                del self._entries[k]
            return len(keys)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hit_count": int(self._hits.value),
                "miss_count": int(self._misses.value),
                "evictions": int(self._evictions.value),
            }
