"""Indexing backpressure: a node-wide in-flight byte budget for writes.

The analog of the reference's coordinating-side memory accounting
(index/IndexingPressure.java): every bulk/index request reserves its
payload bytes before any work happens and releases them when the
operation completes (success OR failure). When the outstanding total
would exceed the limit, the request is rejected up front with the
reference's 429 `es_rejected_execution_exception` — protecting the host
heap long before the HBM breaker (which guards device memory, not the
Python buffers a runaway `_bulk` burst allocates) could engage.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class IndexingPressureRejected(Exception):
    def __init__(self, message: str):
        super().__init__(message)


class IndexingPressure:
    # 10% of a nominal 1 GiB heap, the reference's default ratio
    # (indexing_pressure.memory.limit: 10%).
    DEFAULT_LIMIT = 100 * 1024 * 1024

    def __init__(self, limit_bytes: int | None = None):
        self.limit = (
            int(limit_bytes) if limit_bytes is not None else self.DEFAULT_LIMIT
        )
        self._lock = threading.Lock()
        self.current_bytes = 0
        # Lifetime counters (the reference's *_rejections / total stats).
        self.total_bytes = 0
        self.rejections = 0

    @contextmanager
    def acquire(self, nbytes: int):
        """Reserve nbytes for the duration of one write operation."""
        nbytes = max(0, int(nbytes))
        with self._lock:
            if self.current_bytes + nbytes > self.limit:
                self.rejections += 1
                would = self.current_bytes + nbytes
                raise IndexingPressureRejected(
                    f"rejected execution of coordinating operation "
                    f"[coordinating_and_primary_bytes={self.current_bytes}, "
                    f"operation_bytes={nbytes}, max_coordinating_and_primary_"
                    f"bytes={self.limit}] (would be [{would}])"
                )
            self.current_bytes += nbytes
            self.total_bytes += nbytes
        try:
            yield
        finally:
            with self._lock:
                self.current_bytes -= nbytes

    def stats(self) -> dict:
        with self._lock:
            return {
                "memory": {
                    "current": {
                        "combined_coordinating_and_primary_in_bytes": (
                            self.current_bytes
                        )
                    },
                    "total": {
                        "combined_coordinating_and_primary_in_bytes": (
                            self.total_bytes
                        ),
                        "coordinating_rejections": self.rejections,
                    },
                    "limit_in_bytes": self.limit,
                }
            }
