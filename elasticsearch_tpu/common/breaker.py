"""HBM circuit breaker: device-memory accounting for segment uploads.

The TPU analog of the reference's hierarchical circuit breakers
(indices/breaker/HierarchyCircuitBreakerService.java:51): where the JVM
breakers bound heap for fielddata/request/in-flight, the scarce resource
here is device HBM, consumed by packed segments (postings/position planes,
doc values, vectors). Every engine reserves against one node-level breaker
before a pack and settles to the actual byte count after; a reservation
that would exceed the limit raises BreakerError — surfaced as HTTP 429
circuit_breaking_exception, like the reference's
CircuitBreakingException#durability=PERMANENT.
"""

from __future__ import annotations

import threading
import time
from collections import deque


class BreakerError(Exception):
    """Device-memory budget exceeded (HTTP 429 circuit_breaking_exception)."""

    def __init__(self, wanted: int, used: int, limit: int, label: str):
        super().__init__(
            f"[hbm] Data too large: [{label}] would use {wanted} bytes on "
            f"top of {used} used, larger than the limit of {limit}"
        )
        self.wanted = wanted
        self.used = used
        self.limit = limit


class CircuitBreaker:
    """Byte-budget accounting with reserve/settle/release semantics.

    With a `ledger` attached (obs/device.HbmLedger), every mutation
    WRITES THROUGH to it under the same (label, scope) — the single
    mechanism that keeps breaker accounting and HBM-ledger accounting
    from drifting (the ISSUE-14 consistency law). Labels must come from
    the ledger's label registry (obs/device.LEDGER_LABELS; enforced by
    staticcheck's registry-breaker-label rule at every call site).
    """

    def __init__(self, limit_bytes: int, name: str = "hbm", ledger=None):
        self.limit = int(limit_bytes)
        self.name = name
        self.used = 0
        self.trips = 0
        # Monotonic stamps of recent trips: the health report's
        # device_memory indicator asks "is the breaker refusing
        # allocations NOW", which the cumulative trip count can't answer.
        self._trip_times: deque[float] = deque(maxlen=128)
        self._lock = threading.Lock()
        self.ledger = ledger
        if ledger is not None:
            ledger.breaker = self

    def add(self, n: int, label: str = "segment", scope=None) -> None:
        """Reserve n bytes; raises BreakerError over the limit."""
        from ..faults import fault_point

        # Injectable breaker trip (faults/registry.py `breaker.reserve`):
        # provokes the 429/degraded paths without filling real HBM.
        fault_point("breaker.reserve", breaker=self.name, label=label)
        with self._lock:
            if self.used + n > self.limit:
                self.trips += 1
                self._trip_times.append(time.monotonic())
                raise BreakerError(n, self.used, self.limit, label)
            self.used += n
        if self.ledger is not None:
            self.ledger.register(label, scope, n, breaker_backed=True)

    def add_unchecked(
        self, n: int, label: str = "segment", scope=None
    ) -> None:
        """Account bytes that must land regardless (recovery, settle-up):
        the breaker protects against new allocations, not existing data."""
        with self._lock:
            self.used += n
        if self.ledger is not None:
            self.ledger.register(label, scope, n, breaker_backed=True)

    def release(self, n: int, label: str = "segment", scope=None) -> None:
        with self._lock:
            self.used = max(0, self.used - n)
        if self.ledger is not None:
            self.ledger.release(label, scope, n, breaker_backed=True)

    def trips_recent(self, window_s: float = 60.0) -> int:
        """Trips inside the trailing window (health-indicator input)."""
        floor = time.monotonic() - window_s
        with self._lock:
            return sum(1 for t in self._trip_times if t >= floor)

    def stats(self) -> dict:
        with self._lock:
            return {
                "limit_size_in_bytes": self.limit,
                "estimated_size_in_bytes": self.used,
                "tripped": self.trips,
            }
