"""Ingest pipelines: pre-index document transformation chains.

The analog of the reference's ingest service + ingest-common processors
(server/src/main/java/org/elasticsearch/ingest/IngestService.java,
modules/ingest-common/): a pipeline is an ordered processor list applied
to every document before it reaches the engine, selected per request
(?pipeline=) or per index (settings index.default_pipeline).

Processors (ingest-common subset): set, remove, rename, lowercase,
uppercase, trim, convert, split, join, append, gsub, fail, drop.
Per-processor options: ignore_missing (skip absent fields),
ignore_failure (swallow errors). Field paths use dot notation into
nested objects; `set` values support one-level {{field}} templates
(the reference's mustache value templates).
"""

from __future__ import annotations

import re
from typing import Any, Callable


class PipelineError(Exception):
    """Processor failure (HTTP 400 / per-item bulk error)."""


class DropDocument(Exception):
    """Raised by the drop processor: the document is silently discarded."""


_TEMPLATE_RE = re.compile(r"\{\{\s*([\w.]+)\s*\}\}")
_MISSING = object()  # absent-field sentinel


def _path_get(doc: dict, path: str, default=None):
    if default is None:
        default = _MISSING
    cur: Any = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


def _path_set(doc: dict, path: str, value: Any) -> None:
    parts = path.split(".")
    cur = doc
    for part in parts[:-1]:
        nxt = cur.get(part)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[part] = nxt
        cur = nxt
    cur[parts[-1]] = value


def _path_del(doc: dict, path: str) -> bool:
    parts = path.split(".")
    cur: Any = doc
    for part in parts[:-1]:
        if not isinstance(cur, dict) or part not in cur:
            return False
        cur = cur[part]
    if isinstance(cur, dict) and parts[-1] in cur:
        del cur[parts[-1]]
        return True
    return False


def _render(value: Any, doc: dict) -> Any:
    """{{field}} template substitution in string values."""
    if not isinstance(value, str) or "{{" not in value:
        return value

    def sub(m):
        v = _path_get(doc, m.group(1))
        return "" if v is _MISSING else str(v)

    return _TEMPLATE_RE.sub(sub, value)


def _missing(proc_kind: str, field: str) -> PipelineError:
    return PipelineError(
        f"[{proc_kind}] field [{field}] not present as part of path [{field}]"
    )


class Pipeline:
    def __init__(self, pipeline_id: str, body: dict[str, Any]):
        self.id = pipeline_id
        self.description = body.get("description", "")
        self.body = body
        raw = body.get("processors")
        if not isinstance(raw, list) or not raw:
            raise PipelineError(
                f"pipeline [{pipeline_id}] requires a [processors] array"
            )
        self._steps: list[tuple[str, dict, Callable[[dict, dict], None]]] = []
        for spec in raw:
            if not isinstance(spec, dict) or len(spec) != 1:
                raise PipelineError(
                    "each processor must be an object with exactly one type"
                )
            ((kind, opts),) = spec.items()
            handler = _PROCESSORS.get(kind)
            if handler is None:
                raise PipelineError(
                    f"No processor type exists with name [{kind}]"
                )
            _validate(kind, opts or {})
            self._steps.append((kind, opts or {}, handler))

    def run(self, source: dict[str, Any]) -> dict[str, Any] | None:
        """Transformed copy of the source; None = dropped.

        Deep copy: processors reach into nested objects and extend lists,
        and the caller's original must stay untouched (bulk retries would
        otherwise see pipeline-mangled data)."""
        import copy

        doc = copy.deepcopy(source)
        for kind, opts, handler in self._steps:
            try:
                handler(doc, opts)
            except DropDocument:
                return None
            except re.error as e:
                if not opts.get("ignore_failure"):
                    raise PipelineError(
                        f"[{kind}] invalid pattern: {e}"
                    ) from None
            except PipelineError:
                if not opts.get("ignore_failure"):
                    raise
        return doc


_REQUIRED = {
    "set": ("field", "value"),
    "remove": ("field",),
    "rename": ("field", "target_field"),
    "lowercase": ("field",),
    "uppercase": ("field",),
    "trim": ("field",),
    "convert": ("field", "type"),
    "split": ("field", "separator"),
    "join": ("field", "separator"),
    "append": ("field", "value"),
    "gsub": ("field", "pattern", "replacement"),
    "fail": ("message",),
    "drop": (),
}


def _validate(kind: str, opts: dict) -> None:
    for key in _REQUIRED.get(kind, ()):
        if key not in opts:
            raise PipelineError(
                f"[{kind}] processor requires [{key}]"
            )
    # Regex-bearing processors compile at PUT time, so a broken pattern is
    # a 400 on registration, not a crash on the first indexed document.
    for pattern_key in ("pattern", "separator") if kind in ("gsub", "split") else ():
        if pattern_key in opts:
            try:
                re.compile(opts[pattern_key])
            except re.error as e:
                raise PipelineError(
                    f"[{kind}] invalid [{pattern_key}] pattern: {e}"
                ) from None


def _string_op(kind: str, fn: Callable[[str], str]):
    def handler(doc: dict, opts: dict) -> None:
        field = opts["field"]
        v = _path_get(doc, field)
        if v is _MISSING:
            if opts.get("ignore_missing"):
                return
            raise _missing(kind, field)
        if isinstance(v, list):
            _path_set(doc, field, [fn(str(x)) for x in v])
        else:
            _path_set(doc, field, fn(str(v)))

    return handler


def _p_set(doc: dict, opts: dict) -> None:
    if not opts.get("override", True) and _path_get(
        doc, opts["field"]
    ) is not _MISSING:
        return
    _path_set(doc, opts["field"], _render(opts["value"], doc))


def _p_remove(doc: dict, opts: dict) -> None:
    fields = opts["field"]
    for f in fields if isinstance(fields, list) else [fields]:
        if not _path_del(doc, f) and not opts.get("ignore_missing"):
            raise _missing("remove", f)


def _p_rename(doc: dict, opts: dict) -> None:
    v = _path_get(doc, opts["field"])
    if v is _MISSING:
        if opts.get("ignore_missing"):
            return
        raise _missing("rename", opts["field"])
    if _path_get(doc, opts["target_field"]) is not _MISSING:
        raise PipelineError(
            f"[rename] field [{opts['target_field']}] already exists"
        )
    _path_del(doc, opts["field"])
    _path_set(doc, opts["target_field"], v)


def _p_convert(doc: dict, opts: dict) -> None:
    field = opts["field"]
    v = _path_get(doc, field)
    if v is _MISSING:
        if opts.get("ignore_missing"):
            return
        raise _missing("convert", field)
    target = opts.get("target_field", field)
    ctype = opts["type"]

    def one(x):
        try:
            if ctype == "integer" or ctype == "long":
                return int(x)  # base 10, leading zeros fine (ES parseInt)
            if ctype == "float" or ctype == "double":
                return float(x)
            if ctype == "string":
                return str(x)
            if ctype == "boolean":
                if isinstance(x, bool):
                    return x
                s = str(x).lower()
                if s in ("true", "false"):
                    return s == "true"
                raise ValueError(x)
            if ctype == "auto":
                s = str(x)
                for conv in (int, float):
                    try:
                        return conv(s)
                    except ValueError:
                        pass
                if s.lower() in ("true", "false"):
                    return s.lower() == "true"
                return s
        except (TypeError, ValueError):
            raise PipelineError(
                f"[convert] unable to convert [{x!r}] to {ctype}"
            ) from None
        raise PipelineError(f"[convert] invalid type [{ctype}]")

    _path_set(
        doc, target, [one(x) for x in v] if isinstance(v, list) else one(v)
    )


def _p_split(doc: dict, opts: dict) -> None:
    field = opts["field"]
    v = _path_get(doc, field)
    if v is _MISSING:
        if opts.get("ignore_missing"):
            return
        raise _missing("split", field)
    if not isinstance(v, str):
        raise PipelineError(f"[split] field [{field}] is not a string")
    _path_set(
        doc,
        opts.get("target_field", field),
        re.split(opts["separator"], v),
    )


def _p_join(doc: dict, opts: dict) -> None:
    field = opts["field"]
    v = _path_get(doc, field)
    if v is _MISSING:
        if opts.get("ignore_missing"):
            return
        raise _missing("join", field)
    if not isinstance(v, list):
        raise PipelineError(f"[join] field [{field}] is not a list")
    _path_set(
        doc, opts.get("target_field", field),
        str(opts["separator"]).join(str(x) for x in v),
    )


def _p_append(doc: dict, opts: dict) -> None:
    field = opts["field"]
    value = _render(opts["value"], doc)
    values = value if isinstance(value, list) else [value]
    cur = _path_get(doc, field)
    if cur is _MISSING:
        _path_set(doc, field, list(values))
    elif isinstance(cur, list):
        cur.extend(values)
    else:
        _path_set(doc, field, [cur, *values])


def _p_gsub(doc: dict, opts: dict) -> None:
    field = opts["field"]
    v = _path_get(doc, field)
    if v is _MISSING:
        if opts.get("ignore_missing"):
            return
        raise _missing("gsub", field)
    if not isinstance(v, str):
        raise PipelineError(f"[gsub] field [{field}] is not a string")
    _path_set(
        doc,
        opts.get("target_field", field),
        re.sub(opts["pattern"], opts["replacement"], v),
    )


def _p_fail(doc: dict, opts: dict) -> None:
    raise PipelineError(_render(opts["message"], doc))


def _p_drop(doc: dict, opts: dict) -> None:
    raise DropDocument()


_PROCESSORS: dict[str, Callable[[dict, dict], None]] = {
    "set": _p_set,
    "remove": _p_remove,
    "rename": _p_rename,
    "lowercase": _string_op("lowercase", str.lower),
    "uppercase": _string_op("uppercase", str.upper),
    "trim": _string_op("trim", str.strip),
    "convert": _p_convert,
    "split": _p_split,
    "join": _p_join,
    "append": _p_append,
    "gsub": _p_gsub,
    "fail": _p_fail,
    "drop": _p_drop,
}
