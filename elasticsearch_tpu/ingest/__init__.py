from .pipeline import Pipeline, PipelineError

__all__ = ["Pipeline", "PipelineError"]
